"""Live metrics sampler: OP_STATS as a time series, not a teardown shot.

The harness used to fetch ONE scheduler-telemetry snapshot at teardown —
so a chaos-killed sidecar lost its stats entirely, and nothing could
show throughput/queue-wait/breaker behavior *over time*.  The sampler
polls a fetch callable at a fixed interval for the whole run window and
appends one JSONL sample per tick to ``logs/metrics.jsonl``::

    {"t": <wall s>, "ok": true,  "stats": {<OP_STATS snapshot>}}
    {"t": <wall s>, "ok": false, "error": "<why>"}

Failed ticks are RECORDED, not skipped: a sidecar kill shows up as a
run of ``ok: false`` samples and the restart as the samples resuming —
that visible gap is how chaos SLO verdicts cite the recovery curve.
The last good snapshot stays available (``last``) so teardown can fall
back to it when the sidecar died before the final fetch.

The connection is PERSISTENT with reconnect-on-failure
(:func:`persistent_fetch`): one dial serves every healthy tick — the
1 Hz series stops paying (and accidentally measuring) a TCP dial per
sample — and a dead socket fails exactly one tick (recorded ``ok:
false``, connection dropped) before the next tick re-dials.  A sampler
pinned to one socket forever would die with the first kill and miss
the restart it exists to show; re-dialing only after failure keeps the
kill/restart gap semantics byte-identical to the old dial-per-tick
behavior (regression-tested).

graftscope adds the NODE side of the series: the C++ node emits 1 Hz
machine-parseable ``METRICS`` lines into its own log (common/metrics.cpp,
behind the ``trace`` parameter), and :func:`merge_node_series` mines
``node-*.log`` post-run and appends per-replica records next to the
sidecar samples::

    {"t": <wall s>, "ok": true, "node": "node-0.log",
     "metrics": {"commits": N, "commit_rate": f, "ingress_tx": N,
                 "ingress_bytes": N, "busy": N, "breaker": "closed"}}

``split_samples`` keeps the two sub-series apart for consumers that
reason about the sidecar only (recovery curves, SLO judges), and
``commit_rate_divergence`` turns the per-replica curves into straggler
evidence for the LogParser.

Clocks are injected (``clock``/``wall``/``wait``) — the virtual-clock
tests drive ticks manually, and graftlint's span checker keeps inline
``time.time()`` out of this package.
"""

from __future__ import annotations

import json
import threading
from time import time as _wall_clock


def persistent_fetch(dial, call=None, close=None):
    """Wrap a connection factory into the sampler's ``fetch`` contract
    with ONE reused connection.

    ``dial()`` opens a connection (raises on a dead sidecar — that tick
    records ``ok: false`` and the NEXT tick re-dials); ``call(conn)``
    fetches one snapshot (default: ``conn.stats()``, the SidecarClient
    surface); ``close(conn)`` releases it (default: ``conn.close()``).
    Any ``call`` failure drops the connection before re-raising, so a
    kill mid-run shows the same failed-tick gap a dial-per-tick sampler
    showed, minus the per-tick dial cost on every healthy sample.  The
    returned callable exposes ``.close()`` for teardown; the sampler's
    own ``stop()`` calls it."""
    call = call if call is not None else (lambda conn: conn.stats())
    close = close if close is not None else (lambda conn: conn.close())
    state = {"conn": None}

    def _drop():
        conn, state["conn"] = state["conn"], None
        if conn is not None:
            try:
                close(conn)
            except (OSError, ValueError):
                pass

    def fetch():
        conn = state["conn"]
        if conn is None:
            conn = dial()
            state["conn"] = conn
        try:
            return call(conn)
        except BaseException:
            _drop()
            raise

    fetch.close = _drop
    return fetch


class MetricsSampler:
    def __init__(self, fetch, path: str, interval_s: float = 1.0,
                 wall=_wall_clock, wait=None):
        """``fetch()`` returns one JSON-safe stats snapshot dict (and may
        raise OSError/ConnectionError/ValueError on a dead or garbled
        sidecar); ``wait(seconds) -> bool`` returns True when the
        sampler should stop (default: the stop event's own ``wait``,
        which a test replaces with a virtual clock).

        graftfleet: ``fetch`` may instead be a LIST of ``(endpoint,
        fetch)`` pairs — one per fleet sidecar.  Each tick then writes
        one record per endpoint, tagged ``"endpoint": "<host:port>"``,
        so a kill of sidecar i reads as ok-false ticks on that endpoint
        while the rest of the fleet's series keeps flowing.  ``last``
        still tracks the newest good sample overall; ``last_by_endpoint``
        keeps the per-endpoint fallback teardown needs."""
        if isinstance(fetch, list):
            self._fetches = list(fetch)
        else:
            self._fetches = [(None, fetch)]
        self._fetch = fetch  # kept for the stop()-time closer probe
        self._path = path
        self._interval_s = interval_s
        self._wall = wall
        self._stop = threading.Event()
        self._wait = wait if wait is not None else self._stop.wait
        self._lock = threading.Lock()
        self._file = None
        self._thread = None
        self.samples = 0
        self.ok_samples = 0
        self.last = None  # (wall_ts, snapshot) of the last GOOD sample
        self.last_by_endpoint = {}  # endpoint -> (wall_ts, snapshot)

    # -- one tick (the unit tests drive this directly) -----------------------

    def sample_once(self):
        """Fetch + record one sample per endpoint; returns the record
        written (single-fetch sampler, the legacy contract) or the list
        of records (endpoint list).  None / None entries mean the sink
        failed — telemetry never raises."""
        records = []
        for endpoint, fetch in self._fetches:
            t = self._wall()
            try:
                snap = fetch()
                if not isinstance(snap, dict):
                    raise ValueError(f"snapshot is {type(snap).__name__}, "
                                     "not a dict")
                rec = {"t": t, "ok": True, "stats": snap}
                self.last = (t, snap)
                if endpoint is not None:
                    self.last_by_endpoint[endpoint] = (t, snap)
                self.ok_samples += 1
            except (OSError, ConnectionError, ValueError, RuntimeError) as e:
                rec = {"t": t, "ok": False, "error": f"{e!r:.200}"}
            if endpoint is not None:
                rec["endpoint"] = endpoint
            self.samples += 1
            records.append(rec if self._write(rec) else None)
        if len(self._fetches) == 1 and self._fetches[0][0] is None:
            return records[0]
        return records

    def _write(self, rec: dict) -> bool:
        with self._lock:
            try:
                if self._file is None:
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(json.dumps(rec, sort_keys=True) + "\n")
                self._file.flush()
                return True
            except (OSError, TypeError, ValueError):
                return False

    # -- thread lifecycle ----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-sampler")
        self._thread.start()
        return self

    def _run(self):
        while True:
            self.sample_once()
            if self._wait(self._interval_s):
                return

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        for _, fetch in self._fetches:
            closer = getattr(fetch, "close", None)
            if closer is not None:
                try:
                    closer()
                except (OSError, ValueError):
                    pass
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def read_samples(path: str):
    """``metrics.jsonl`` -> ``(samples, malformed)`` with torn lines
    skipped and counted (a SIGKILLed harness can cut a line short;
    spans.parse_jsonl is the shared tolerance contract)."""
    from .spans import parse_jsonl

    try:
        with open(path, errors="replace") as f:
            text = f.read()
    except OSError:
        return [], 0
    return parse_jsonl(
        text,
        lambda rec: isinstance(rec.get("t"), (int, float))
        and "ok" in rec)


# -- graftscope: the C++ node's METRICS series -------------------------------

# The FROZEN node METRICS line grammar (common/metrics.cpp emit_sample;
# graftlint's obsgrammar checker cross-checks the two sides): the log
# prefix is the node's standard grammar, the payload is append-only
# key=value.  Torn fragments simply don't match — tolerance for free,
# the parse_node_trace convention.
# The graftingress admission-verify suffix (verified/forged/vq) is an
# optional group so logs from pre-signed-ingress builds keep parsing.
_NODE_METRICS_RE = (r"\[(\S+Z) \w+ [^\]]+\] METRICS "
                    r"commits=(\d+) commit_rate=([0-9.]+) "
                    r"ingress_tx=(\d+) ingress_bytes=(\d+) "
                    r"busy=(\d+) breaker=(\w+)"
                    r"(?: verified=(\d+) forged=(\d+) vq=(\d+))?")


def parse_node_metrics(log: str, host: str = "node") -> list:
    """One node log -> metrics.jsonl-shaped records (see module doc)."""
    import re

    from .trace import _to_posix

    records = []
    for (ts, commits, rate, itx, ibytes, busy, breaker,
         verified, forged, vq) in re.findall(_NODE_METRICS_RE, log):
        try:
            t = _to_posix(ts)
            metrics = {"commits": int(commits),
                       "commit_rate": float(rate),
                       "ingress_tx": int(itx),
                       "ingress_bytes": int(ibytes),
                       "busy": int(busy),
                       "breaker": breaker}
            if verified:
                metrics["verified"] = int(verified)
                metrics["forged"] = int(forged)
                metrics["vq"] = int(vq)
        except ValueError:
            continue
        records.append({"t": t, "ok": True, "node": host,
                        "metrics": metrics})
    return records


def collect_node_series(directory: str) -> list:
    """Mine every ``node-*.log`` in a logs directory -> node records,
    sorted by wall stamp."""
    import os
    from glob import glob

    records = []
    for path in sorted(glob(os.path.join(directory, "node-*.log"))):
        try:
            with open(path, errors="replace") as f:
                log = f.read()
        except OSError:
            continue
        records.extend(parse_node_metrics(log, host=os.path.basename(path)))
    records.sort(key=lambda r: r["t"])
    return records


def merge_node_series(directory: str, path: str | None = None) -> int:
    """Append the mined node series into ``<directory>/metrics.jsonl``
    (creating it when only the node side traced) so the one artifact
    carries per-replica series next to the sidecar's.  Idempotent: if
    the file already holds node records (a re-parse of the same logs
    dir), nothing is appended.  Returns the record count appended —
    best-effort, 0 on any failure (telemetry never raises)."""
    import os

    target = path or os.path.join(directory, "metrics.jsonl")
    try:
        existing, _ = read_samples(target)
        if any("node" in s for s in existing):
            return 0
        records = collect_node_series(directory)
        if not records:
            return 0
        with open(target, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return len(records)
    except (OSError, TypeError, ValueError):
        return 0


def split_samples(samples):
    """One mixed metrics.jsonl series -> ``(sidecar, node)`` sub-series.
    Consumers that reason about the sidecar (recovery curves, baseline
    SLO judges, the throughput plot) must not see node records — a
    replica's ok=true tick would otherwise read as sidecar telemetry
    resuming."""
    sidecar = [s for s in samples if "node" not in s]
    node = [s for s in samples if "node" in s]
    return sidecar, node


def replica_commit_rates(node_samples) -> dict:
    """Node records -> ``{host: mean sampled commit rate}`` over the run
    window (the straggler-detection input)."""
    by_host: dict = {}
    for s in node_samples:
        metrics = s.get("metrics") or {}
        rate = metrics.get("commit_rate")
        if isinstance(rate, (int, float)):
            by_host.setdefault(s["node"], []).append(float(rate))
    return {host: sum(v) / len(v) for host, v in by_host.items() if v}


def commit_rate_divergence(node_samples, threshold: float = 0.7) -> dict:
    """Straggler detection over the sampled per-replica commit rates::

        {"median": <committee median mean-rate>,
         "rates": {host: mean_rate},
         "stragglers": [{"host", "rate", "ratio"}]}   # ratio < threshold

    A replica whose mean sampled commit rate falls below ``threshold``
    of the committee median diverges — it commits, but late enough that
    its view of the chain lags the committee (the LogParser surfaces
    this as a note; strict mode is unaffected, divergence is evidence,
    not failure)."""
    from statistics import median

    rates = replica_commit_rates(node_samples)
    if len(rates) < 2:
        return {"median": None, "rates": rates, "stragglers": []}
    med = median(rates.values())
    stragglers = []
    if med > 0:
        for host, rate in sorted(rates.items()):
            ratio = rate / med
            if ratio < threshold:
                stragglers.append({"host": host,
                                   "rate": round(rate, 3),
                                   "ratio": round(ratio, 3)})
    return {"median": round(med, 3), "rates":
            {h: round(r, 3) for h, r in rates.items()},
            "stragglers": stragglers}


def recovery_curve(samples, event_wall: float) -> dict:
    """What the sampled time series says about one fault event::

        {"resumed": bool,        # a good sample exists after the event
         "resume_ms": float,     # event -> first good sample after
         "failed_ticks": int,    # ok=false samples after the event,
                                 # before telemetry resumed
         "samples_after": int}

    This is the curve behind an SLO verdict: "recovered in 2.1 s" plus
    "telemetry blacked out for 3 failed ticks" tells the reader the
    sidecar actually died and came back, where the commit-only scalar
    could not distinguish a kill from a hiccup."""
    after = sorted((s for s in samples if s["t"] > event_wall),
                   key=lambda s: s["t"])
    failed = 0
    for s in after:
        if s.get("ok"):
            return {"resumed": True,
                    "resume_ms": round((s["t"] - event_wall) * 1e3, 3),
                    "failed_ticks": failed,
                    "samples_after": len(after)}
        failed += 1
    return {"resumed": False, "resume_ms": None,
            "failed_ticks": failed, "samples_after": len(after)}

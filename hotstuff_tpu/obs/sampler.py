"""Live metrics sampler: OP_STATS as a time series, not a teardown shot.

The harness used to fetch ONE scheduler-telemetry snapshot at teardown —
so a chaos-killed sidecar lost its stats entirely, and nothing could
show throughput/queue-wait/breaker behavior *over time*.  The sampler
polls a fetch callable at a fixed interval for the whole run window and
appends one JSONL sample per tick to ``logs/metrics.jsonl``::

    {"t": <wall s>, "ok": true,  "stats": {<OP_STATS snapshot>}}
    {"t": <wall s>, "ok": false, "error": "<why>"}

Failed ticks are RECORDED, not skipped: a sidecar kill shows up as a
run of ``ok: false`` samples and the restart as the samples resuming —
that visible gap is how chaos SLO verdicts cite the recovery curve.
The last good snapshot stays available (``last``) so teardown can fall
back to it when the sidecar died before the final fetch.

Every tick dials a FRESH connection: a sampler pinned to one socket
would die with the first kill and miss the restart it exists to show.

Clocks are injected (``clock``/``wall``/``wait``) — the virtual-clock
tests drive ticks manually, and graftlint's span checker keeps inline
``time.time()`` out of this package.
"""

from __future__ import annotations

import json
import threading
from time import time as _wall_clock


class MetricsSampler:
    def __init__(self, fetch, path: str, interval_s: float = 1.0,
                 wall=_wall_clock, wait=None):
        """``fetch()`` returns one JSON-safe stats snapshot dict (and may
        raise OSError/ConnectionError/ValueError on a dead or garbled
        sidecar); ``wait(seconds) -> bool`` returns True when the
        sampler should stop (default: the stop event's own ``wait``,
        which a test replaces with a virtual clock)."""
        self._fetch = fetch
        self._path = path
        self._interval_s = interval_s
        self._wall = wall
        self._stop = threading.Event()
        self._wait = wait if wait is not None else self._stop.wait
        self._lock = threading.Lock()
        self._file = None
        self._thread = None
        self.samples = 0
        self.ok_samples = 0
        self.last = None  # (wall_ts, snapshot) of the last GOOD sample

    # -- one tick (the unit tests drive this directly) -----------------------

    def sample_once(self):
        """Fetch + record one sample; returns the record written (or
        None once the sink failed — telemetry never raises)."""
        t = self._wall()
        try:
            snap = self._fetch()
            if not isinstance(snap, dict):
                raise ValueError(f"snapshot is {type(snap).__name__}, "
                                 "not a dict")
            rec = {"t": t, "ok": True, "stats": snap}
            self.last = (t, snap)
            self.ok_samples += 1
        except (OSError, ConnectionError, ValueError, RuntimeError) as e:
            rec = {"t": t, "ok": False, "error": f"{e!r:.200}"}
        self.samples += 1
        return rec if self._write(rec) else None

    def _write(self, rec: dict) -> bool:
        with self._lock:
            try:
                if self._file is None:
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(json.dumps(rec, sort_keys=True) + "\n")
                self._file.flush()
                return True
            except (OSError, TypeError, ValueError):
                return False

    # -- thread lifecycle ----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-sampler")
        self._thread.start()
        return self

    def _run(self):
        while True:
            self.sample_once()
            if self._wait(self._interval_s):
                return

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def read_samples(path: str):
    """``metrics.jsonl`` -> ``(samples, malformed)`` with torn lines
    skipped and counted (a SIGKILLed harness can cut a line short;
    spans.parse_jsonl is the shared tolerance contract)."""
    from .spans import parse_jsonl

    try:
        with open(path, errors="replace") as f:
            text = f.read()
    except OSError:
        return [], 0
    return parse_jsonl(
        text,
        lambda rec: isinstance(rec.get("t"), (int, float))
        and "ok" in rec)


def recovery_curve(samples, event_wall: float) -> dict:
    """What the sampled time series says about one fault event::

        {"resumed": bool,        # a good sample exists after the event
         "resume_ms": float,     # event -> first good sample after
         "failed_ticks": int,    # ok=false samples after the event,
                                 # before telemetry resumed
         "samples_after": int}

    This is the curve behind an SLO verdict: "recovered in 2.1 s" plus
    "telemetry blacked out for 3 failed ticks" tells the reader the
    sidecar actually died and came back, where the commit-only scalar
    could not distinguish a kill from a hiccup."""
    after = sorted((s for s in samples if s["t"] > event_wall),
                   key=lambda s: s["t"])
    failed = 0
    for s in after:
        if s.get("ok"):
            return {"resumed": True,
                    "resume_ms": round((s["t"] - event_wall) * 1e3, 3),
                    "failed_ticks": failed,
                    "samples_after": len(after)}
        failed += 1
    return {"resumed": False, "resume_ms": None,
            "failed_ticks": failed, "samples_after": len(after)}

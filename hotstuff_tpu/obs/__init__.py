"""grafttrace: cross-layer span tracing + live metrics sampling.

The repo's perf and chaos claims used to rest on end-of-run aggregates
(LogParser scraping logs, one OP_STATS snapshot at teardown).  This
package makes every claim attributable to a *place in the pipeline*:

``spans``
    The span record schema and the :class:`Tracer` JSONL writer the
    sidecar threads its hot-path stages through (admit -> queue ->
    pack -> dispatch -> device -> reply), tagged with the request rid
    and scheduler class.  Timestamps always come from the injected
    clock — graftlint's ``unclosed-span`` checker enforces both that
    and the begin/end pairing discipline.

``trace``
    The collector/merger: parses the C++ node's ``TRACE`` lines
    (proposal -> verify_submit -> verify_reply -> commit, keyed on
    block digest + round), estimates per-host clock offsets (RTT
    midpoint), stitches per-block commit traces across replica logs,
    computes the critical-path breakdown (p50/p99 per stage), and
    exports a Chrome-trace-event / Perfetto-loadable ``trace.json``.

``sampler``
    The live metrics sampler: polls OP_STATS at a fixed interval
    DURING the run window (not only at teardown), appending time-series
    samples to ``logs/metrics.jsonl`` so throughput/queue-wait over
    time can be plotted, chaos SLO verdicts can cite the recovery
    curve, and a chaos-killed sidecar's telemetry survives as the last
    good sample.  graftscope adds the C++ node's 1 Hz ``METRICS`` line
    reader: per-replica commit-rate/ingress/breaker series merged into
    the same artifact, plus straggler detection over them.

graftscope closes the attribution loop between the two halves: the
protocol-v5 context tag carries each block's digest through the verify
RPC, the sidecar tags its stage spans with it, and ``trace`` joins the
chains back onto the blocks — ``logs/trace.json`` nests device time
inside each block's verify segment, with ``join_rate`` saying what
fraction of verify-traced committed blocks carried a chain.
"""

from __future__ import annotations

from .sampler import (
    MetricsSampler,
    commit_rate_divergence,
    merge_node_series,
    parse_node_metrics,
    persistent_fetch,
    read_samples,
    recovery_curve,
    split_samples,
)
from .spans import SpanError, Tracer, parse_spans
from .trace import (
    build_run_trace,
    chain_spans,
    chrome_trace,
    clock_offset,
    critical_path,
    join_blocks,
    parse_node_trace,
    stitch_blocks,
    write_run_trace,
)

__all__ = [
    "MetricsSampler",
    "SpanError",
    "Tracer",
    "build_run_trace",
    "chain_spans",
    "chrome_trace",
    "clock_offset",
    "commit_rate_divergence",
    "critical_path",
    "join_blocks",
    "merge_node_series",
    "parse_node_metrics",
    "parse_node_trace",
    "parse_spans",
    "persistent_fetch",
    "read_samples",
    "recovery_curve",
    "split_samples",
    "stitch_blocks",
    "write_run_trace",
]

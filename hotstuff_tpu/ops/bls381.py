"""BLS12-381 pairing verification on TPU: batched Fq12 arithmetic + the
final exponentiation, with host-precomputed Miller line values.

Work split (mirrors the Ed25519 engine's host/device boundary):
* HOST (python bigints, ~2 ms/pairing): point decode/validation,
  hash-to-G2, public-key aggregation, and the Miller loop's line values —
  the curve bookkeeping is O(64) affine operations whose cost is
  negligible next to the extension-field tower.
* DEVICE (the FLOPs): the Miller accumulation f <- f^2 * l_i over the 63
  BLS_X bits and the ~1,600-multiplication final exponentiation, all as
  batched Fq12 arithmetic on the Montgomery conv engine (field381.py).

An Fq12 element is a (..., 12, 48) int32 array — a flat degree-12
polynomial over Fq (modulus w^12 - 2w^6 + 2, matching the host reference
offchain/bls12381.py) with Montgomery-form coefficient limbs. Products
ride ONE grouped conv per 144-coefficient multiply; Frobenius maps are
precomputed 12x12 Fq matrices, so f^(q^k) is one more conv round — which
also powers an inversion-free path everywhere (the BLS_X sign conjugation
cancels in the == 1 check, and the one true inversion in the easy part of
the final exponentiation uses the field-norm trick).

Reference parity: the aggregate-verification capability of
off-chain-benchmarking/bls.py:20-32 and the production bench's
filecoin-style BLS aggregate path, re-designed TPU-first.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field381 as F
from ..offchain import bls12381 as host

Q = host.Q
BLS_X = host.BLS_X

# Miller schedule: per bit of BLS_X (after the leading 1), a doubling line
# and, on set bits, an addition line. Fixed at import time.
_BITS = [int(b) for b in bin(BLS_X)[3:]]
N_STEPS = len(_BITS)


# ---------------------------------------------------------------------------
# Host-side preparation
# ---------------------------------------------------------------------------

def host_fq12_to_mont_limbs(x) -> np.ndarray:
    """Host Fq12 tuple (12 ints) -> (12, 48) Montgomery limb array."""
    return np.stack([F.to_limbs(c * F.R % Q) for c in x])


def miller_lines(p_g1, q_g2) -> np.ndarray:
    """Run the host Miller loop recording line values: (N_STEPS, 2, 12, 48)
    Montgomery limbs. Slot 0 is the doubling line, slot 1 the addition
    line (identity 1 on clear bits so the device body is uniform)."""
    qt = host._twist(q_g2)
    pf = host._cast_g1_fq12(p_g1)
    one = host.FQ12_ONE
    rpt = qt
    out = np.zeros((N_STEPS, 2, 12, F.NLIMBS), np.int32)
    for i, bit in enumerate(_BITS):
        out[i, 0] = host_fq12_to_mont_limbs(host._linefunc(rpt, rpt, pf))
        rpt = host._add(rpt, rpt, host._fq12)
        if bit:
            out[i, 1] = host_fq12_to_mont_limbs(host._linefunc(rpt, qt, pf))
            rpt = host._add(rpt, qt, host._fq12)
        else:
            out[i, 1] = host_fq12_to_mont_limbs(one)
    return out


# Frobenius matrices: FROB[k][i] = (w^i)^(q^k) as a host Fq12 element, so
# f^(q^k) = sum_i f_i * FROB[k][i] (coefficients of Fq are Frobenius-fixed).
def _frob_matrices():
    w = tuple(1 if i == 1 else 0 for i in range(12))
    w_q = host.fq12_pow(w, Q)  # one 381-bit host exponentiation
    mats = {}
    basis = [w]
    for i in range(2, 12):
        basis.append(host.fq12_mul(basis[-1], w))
    basis = [tuple(1 if j == 0 else 0 for j in range(12))] + basis  # w^0..w^11

    def apply_frob(x, wq_pows):
        acc = tuple(0 for _ in range(12))
        for i, c in enumerate(x):
            if c:
                acc = host.fq12_add(acc, host.fq12_scalar(wq_pows[i], c))
        return acc

    wq_pows = [tuple(1 if j == 0 else 0 for j in range(12))]
    for i in range(1, 12):
        wq_pows.append(host.fq12_mul(wq_pows[-1], w_q))

    cur = basis
    for k in range(1, 12):
        cur = [apply_frob(b, wq_pows) for b in cur]
        mats[k] = np.stack([host_fq12_to_mont_limbs(row) for row in cur])
    return mats  # mats[k]: (12, 12, 48) — row i = (w^i)^(q^k)


_FROB = _frob_matrices()

# Final-exponentiation hard part: (q^4 - q^2 + 1) / r.
_HARD_EXP = (Q ** 4 - Q ** 2 + 1) // host.R
assert (Q ** 12 - 1) % host.R == 0
assert (Q ** 6 - 1) * (Q ** 2 + 1) * _HARD_EXP == (Q ** 12 - 1) // host.R


# ---------------------------------------------------------------------------
# Device Fq12 arithmetic
# ---------------------------------------------------------------------------

def fq12_one(batch_shape=()) -> jnp.ndarray:
    one = np.zeros((12, F.NLIMBS), np.int32)
    one[0] = F.to_limbs(F.R_MOD_Q)
    return jnp.broadcast_to(jnp.asarray(one), (*batch_shape, 12, F.NLIMBS))


def fq12_mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(..., 12, 48) x (..., 12, 48): all 144 coefficient products in one
    grouped conv, anti-diagonal accumulation, w^12 = 2w^6 - 2 fold."""
    prod = F.mont_mul(x[..., :, None, :], y[..., None, :, :])
    # coeff[k] = sum_{i+j=k} prod[i, j]; <= 12 weak terms -> limbs < 2^13,
    # value < 2^389: reduce_sum brings each back to weak form (anything
    # less lets the top limb creep past the conv exactness bound).
    coeffs = []
    for k in range(23):
        terms = [prod[..., i, k - i, :]
                 for i in range(max(0, k - 11), min(12, k + 1))]
        coeffs.append(F.reduce_sum(sum(terms)))
    # fold degrees 22..12 down (top-first so cascades resolve)
    for d in range(22, 11, -1):
        c2 = F.add(coeffs[d], coeffs[d])
        coeffs[d - 6] = F.add(coeffs[d - 6], c2)
        coeffs[d - 12] = F.sub(coeffs[d - 12], c2)
    # the folded coefficients carry one add + one biased sub on top of a
    # weak element; one more reduce_sum restores the invariant
    return jnp.stack([F.reduce_sum(c) for c in coeffs[:12]], axis=-2)


def fq12_sqr(x: jnp.ndarray) -> jnp.ndarray:
    return fq12_mul(x, x)


def fq12_frobenius(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """f^(q^k) via the precomputed basis-image matrix: one conv round."""
    mat = jnp.asarray(_FROB[k])  # (12i, 12j, 48)
    prod = F.mont_mul(x[..., :, None, :], mat)  # (..., 12i, 12j, 48)
    return F.reduce_sum(jnp.sum(prod, axis=-3))


def fq12_inv(x: jnp.ndarray) -> jnp.ndarray:
    """Field-norm inversion: g = prod_{k=1..11} f^(q^k); N = f*g lies in
    Fq (its 0-coefficient), so f^{-1} = g * N^{-1}."""
    g = fq12_frobenius(x, 1)
    for k in range(2, 12):
        g = fq12_mul(g, fq12_frobenius(x, k))
    n = fq12_mul(x, g)
    n0_inv = F.inv(n[..., 0, :])
    return F.mont_mul(g, n0_inv[..., None, :])


def fq12_pow_const(x: jnp.ndarray, exponent: int,
                   window: int = 4) -> jnp.ndarray:
    """x^exponent, static exponent (field381.pow_windowed over Fq12)."""
    return F.pow_windowed(x, exponent, fq12_mul, fq12_one(x.shape[:-2]),
                          window)


# ---------------------------------------------------------------------------
# Pairing pieces
# ---------------------------------------------------------------------------

def miller_accumulate(lines: jnp.ndarray) -> jnp.ndarray:
    """lines (..., N_STEPS, 2, 12, 48) -> Miller value (without the BLS_X
    sign conjugation — it cancels in the == 1 check after final exp)."""
    batch_shape = lines.shape[:-4]
    f0 = fq12_one(batch_shape)
    steps = jnp.moveaxis(lines, -4, 0)

    def body(f, step):
        f = fq12_mul(fq12_sqr(f), step[..., 0, :, :])
        f = fq12_mul(f, step[..., 1, :, :])
        return f, None

    f, _ = jax.lax.scan(body, f0, steps)
    return f


def final_exponentiate(f: jnp.ndarray) -> jnp.ndarray:
    """f^((q^12-1)/r): easy part via Frobenius + norm-inversion, hard part
    as one windowed exponentiation by (q^4 - q^2 + 1)/r."""
    f1 = fq12_mul(fq12_frobenius(f, 6), fq12_inv(f))      # f^(q^6 - 1)
    f2 = fq12_mul(fq12_frobenius(f1, 2), f1)              # ^(q^2 + 1)
    return fq12_pow_const(f2, _HARD_EXP)


def is_one(f: jnp.ndarray) -> jnp.ndarray:
    """(..., 12, 48) Montgomery Fq12 -> (...,) bool: f == 1."""
    canon = F.from_mont(f)
    one = jnp.zeros_like(canon).at[..., 0, 0].set(1)
    return jnp.all(canon == one, axis=(-1, -2))


def pairings_check(lines: jnp.ndarray) -> jnp.ndarray:
    """lines (..., P, N_STEPS, 2, 12, 48): P pairings multiplied under ONE
    final exponentiation -> (...,) bool (product == 1)."""
    fs = miller_accumulate(jnp.moveaxis(lines, -5, 0))  # (P, ..., 12, 48)
    f = fs[0]
    for i in range(1, fs.shape[0]):
        f = fq12_mul(f, fs[i])
    return is_one(final_exponentiate(f))


pairings_check_jit = jax.jit(pairings_check)


def selfcheck() -> None:
    """Backend exactness guard for the BLS tower (sidecar/bench startup):
    exercises the fq12_mul fold path — whose coefficient sums run closer
    to the f32 conv bound than plain mont_mul — against the host
    reference. Raises on any mismatch; fix with
    HOTSTUFF_TPU_MUL_PRECISION=highest."""
    F.mul_selfcheck()
    rng = np.random.default_rng(17)
    x = tuple(int.from_bytes(rng.bytes(48), "little") % Q for _ in range(12))
    y = tuple(int.from_bytes(rng.bytes(48), "little") % Q for _ in range(12))
    dx = jnp.asarray(host_fq12_to_mont_limbs(x))[None]
    dy = jnp.asarray(host_fq12_to_mont_limbs(y))[None]
    got = np.asarray(F.from_mont(fq12_mul(dx, dy)))[0]
    want = host.fq12_mul(x, y)
    if tuple(F.from_limbs(r) for r in got) != want:
        raise AssertionError(
            "fq12 multiply is not exact on this backend; set "
            "HOTSTUFF_TPU_MUL_PRECISION=highest")


# ---------------------------------------------------------------------------
# Aggregate verification (host orchestration + device check)
# ---------------------------------------------------------------------------

def verify_aggregate_common(pks, msg: bytes, agg_sig) -> bool:
    """Same-message aggregate verify (the QC shape: 2f+1 votes on one
    digest): e(apk, H(m)) * e(-g1, agg_sig) == 1, pairing math on device.
    pks: list of host G1 points; agg_sig: host G2 point.
    """
    # Same input validation as the host reference: a malformed signature
    # must reject, not crash the Miller-line precomputation.
    if agg_sig is None or not host.g2_on_curve(agg_sig):
        return False
    apk = None
    for pk in pks:
        if pk is None or not host.g1_on_curve(pk):
            return False
        apk = pk if apk is None else host.g1_add(apk, pk)
    if apk is None:
        return False
    h = host.hash_to_g2(msg)
    neg_g1 = host.g1_neg(host.g1_generator())
    lines = np.stack([miller_lines(apk, h),
                      miller_lines(neg_g1, agg_sig)])
    return bool(np.asarray(pairings_check_jit(jnp.asarray(lines))))


def multi_pairing_rows(pks, msgs, agg_sig):
    """Validate a distinct-message aggregate statement and build its n+1
    Miller-line rows (votes + the -g1/agg row). Returns None if any input
    is malformed — the ONE validation both the single-chip and the
    mesh-sharded verifier share, so they can never accept different
    inputs."""
    if len(pks) != len(msgs) or not pks:
        return None
    if agg_sig is None or not host.g2_on_curve(agg_sig):
        return None
    rows = []
    for pk, msg in zip(pks, msgs):
        if pk is None or not host.g1_on_curve(pk):
            return None
        rows.append(miller_lines(pk, host.hash_to_g2(msg)))
    rows.append(miller_lines(host.g1_neg(host.g1_generator()), agg_sig))
    return rows


def verify_aggregate_multi(pks, msgs, agg_sig) -> bool:
    """Distinct-message aggregate verify (the TC shape: 2f+1 timeout votes
    over per-round digests, consensus/src/messages.rs:307-313):
    prod e(pk_i, H(m_i)) * e(-g1, agg) == 1, all n+1 Miller loops batched
    under ONE final exponentiation on device.  Compiles one program per
    vote count; a committee's TC size is fixed at 2f+1, so that is a
    single shape in practice."""
    rows = multi_pairing_rows(pks, msgs, agg_sig)
    if rows is None:
        return False
    return bool(np.asarray(pairings_check_jit(jnp.asarray(np.stack(rows)))))

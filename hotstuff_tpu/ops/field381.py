"""GF(q) arithmetic for BLS12-381, batched, in JAX — the base layer of the
TPU pairing engine (ops/bls381.py).

Same substrate philosophy as field25519.py (8-bit limbs in int32 lanes,
depthwise-conv schoolbook products, parallel carries, no data-dependent
control flow), but q = 0x1a0111ea...aaab has no special form, so reduction
is **Montgomery** with R = 2^384:

* elements live in Montgomery form x~ = x*R mod q as (..., 48) int32 limb
  arrays in "weak" form (limbs < 2^9, value < 2^385 — the REDC digit bound
  keeps this stable across arbitrarily long chains);
* mont_mul does conv(48x48) -> wide carry -> m = T*q' mod R (conv + carry
  with truncation) -> T + m*q (conv) -> exact /R via a float32 carry-out
  dot (the low half's true value is divisible by 2^384, so its carry into
  limb 48 is a small integer recovered exactly in f32).

Reference parity: this underpins the BLS half of the reference's signature
benchmarking (off-chain-benchmarking/bls.py, production/src/main.rs BLS
aggregate path), re-built TPU-first.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NLIMBS = 48
LIMB_BITS = 8
LIMB_MASK = 255

Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 1 << 384
R_MOD_Q = R % Q
R2_MOD_Q = R * R % Q
# q' = -q^{-1} mod R (Montgomery constant)
QPRIME = (-pow(Q, -1, R)) % R

# Same escape hatch as field25519: HIGH (bf16x3) is measured exact for
# this workload's <= 2^23.9 coefficient sums; if a backend ever lowers it
# non-exactly, mul_selfcheck trips and the env var forces HIGHEST.
import os as _os

_PRECISION = {
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}[_os.environ.get("HOTSTUFF_TPU_MUL_PRECISION", "high").lower()]


def to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    return np.array([(int(x) >> (8 * i)) & 0xFF for i in range(n)],
                    dtype=np.int32)


def from_limbs(limbs) -> int:
    limbs = np.asarray(limbs, dtype=np.int64).reshape(-1)
    return sum(int(v) << (8 * i) for i, v in enumerate(limbs))


_Q_LIMBS = to_limbs(Q)
_QPRIME_LIMBS = to_limbs(QPRIME)
# 64q bias for subtraction: every limb dominates a weak limb (< 2^9), and
# the value is a multiple of q, invisible to Montgomery arithmetic. 64q is
# the smallest power-of-two multiple whose top byte survives the borrow
# spreading below with >= 511 left in limb 47.
_BIAS = [(64 * Q >> (8 * i)) & 0xFF for i in range(NLIMBS)]
_BIAS[NLIMBS - 1] += (64 * Q >> (8 * NLIMBS)) << 8  # fold spill into limb 47
# Spread so every limb >= 511 (dominates any weak limb of b): borrow units
# of 256 from the limb above, ascending so fixed limbs stay fixed.
for _i in range(NLIMBS - 1):
    while _BIAS[_i] < 511:
        _BIAS[_i] += 256
        _BIAS[_i + 1] -= 1
_BIAS_ARR = np.asarray(_BIAS, dtype=np.int32)
assert (_BIAS_ARR >= 511).all(), "subtraction bias must dominate weak limbs"
assert sum(int(v) << (8 * i) for i, v in enumerate(_BIAS_ARR)) == 64 * Q


def constant(x: int) -> jnp.ndarray:
    """Canonical (non-Montgomery) constant as (48,) limbs."""
    return jnp.asarray(to_limbs(x % Q))


def mont_constant(x: int) -> jnp.ndarray:
    """Constant in Montgomery form."""
    return jnp.asarray(to_limbs(x * R % Q))


# ---------------------------------------------------------------------------
# Carries
# ---------------------------------------------------------------------------

def _carry_step_plain(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry step WITHOUT wraparound: the carry out of the top
    limb moves into a fresh position only if the array has room; callers
    size arrays so the top limb's carry is representable (value bounds
    guarantee the top limb stays < 2^9 after the final step)."""
    lo = x & LIMB_MASK
    hi = x >> LIMB_BITS
    shifted = jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
    return lo + shifted


def weak_carry(x: jnp.ndarray, steps: int = 3) -> jnp.ndarray:
    """Bring limbs below ~2^9 (inputs < 2^24-ish need 3 steps). The top
    limb's overflow is kept IN PLACE (weight 256 per unit), so the value
    is preserved only when the caller guarantees it fits the array — the
    per-call-site bound comments establish that."""
    for _ in range(steps):
        lo = x & LIMB_MASK
        hi = x >> LIMB_BITS
        shifted = jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
        # keep the top limb's overflow in place (weight 256 per unit)
        top_keep = jnp.zeros_like(x).at[..., -1].set(
            (x[..., -1] >> LIMB_BITS) << LIMB_BITS)
        x = lo + shifted + top_keep
    return x


def trunc_carry(x: jnp.ndarray, steps: int = 3) -> jnp.ndarray:
    """Carry steps that DROP overflow out of the top limb — i.e. arithmetic
    mod 2^(8*nlimbs). Used for the Montgomery m = T*q' mod R step."""
    for _ in range(steps):
        lo = x & LIMB_MASK
        hi = x >> LIMB_BITS
        shifted = jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
        x = lo + shifted
    return x


# ---------------------------------------------------------------------------
# Schoolbook limb product (depthwise conv, same pattern as field25519.mul)
# ---------------------------------------------------------------------------

def _conv_product(a: jnp.ndarray, b: jnp.ndarray, nb: int) -> jnp.ndarray:
    """(..., na) x (..., nb) limb arrays -> (..., na+nb-1) coefficient
    array (exact in f32: weak limbs < 2^9, <= 48 terms per coefficient)."""
    na = a.shape[-1]
    batch_shape = a.shape[:-1]
    n = 1
    for d in batch_shape:
        n *= d
    lhs = a.reshape(1, n, na).astype(jnp.float32)
    rhs = jnp.flip(b.reshape(n, 1, nb), -1).astype(jnp.float32)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(nb - 1, nb - 1)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=n, precision=_PRECISION,
    ).reshape(*batch_shape, na + nb - 1)
    return out.astype(jnp.int32)


def _conv_by_const(a: jnp.ndarray, const_limbs: np.ndarray) -> jnp.ndarray:
    """(..., na) weak limbs times a fixed 48-limb constant."""
    c = jnp.broadcast_to(jnp.asarray(const_limbs),
                         (*a.shape[:-1], NLIMBS))
    return _conv_product(a, c, NLIMBS)


# ---------------------------------------------------------------------------
# Montgomery multiply / add / sub
# ---------------------------------------------------------------------------

_POW_LOW = (2.0 ** (8 * np.arange(NLIMBS) - 384)).astype(np.float32)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """REDC(a*b): both in Montgomery weak form -> Montgomery weak form.
    Inputs broadcast against each other (the Fq12 tower relies on it)."""
    a, b = jnp.broadcast_arrays(a, b)
    t = _conv_product(a, b, NLIMBS)                    # 95 coeffs < 2^24
    t = weak_carry(jnp.pad(t, [(0, 0)] * (t.ndim - 1) + [(0, 1)]), 3)
    t_lo = t[..., :NLIMBS]
    m = trunc_carry(_conv_by_const(t_lo, _QPRIME_LIMBS)[..., :NLIMBS], 3)
    mq = _conv_by_const(m, _Q_LIMBS)                   # 95 coeffs
    t2 = t + jnp.pad(mq, [(0, 0)] * (mq.ndim - 1) + [(0, 1)])
    t2 = weak_carry(t2, 3)
    # (t + m*q) is divisible by R; recover the low half's carry-out into
    # limb 48 exactly in f32 (it is a small integer; digits < 2^10).
    c = jnp.round(jnp.sum(t2[..., :NLIMBS].astype(jnp.float32) * _POW_LOW,
                          axis=-1)).astype(jnp.int32)
    hi = t2[..., NLIMBS:].at[..., 0].add(c)
    return weak_carry(hi, 1)


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, a)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Plain limb add + one carry step (weak in, weak out; mod nothing —
    values stay < 2^386, safely inside the REDC input bound)."""
    return weak_carry(a + b, 1)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b + 64q (bias keeps limbs nonnegative; value changes by a
    multiple of q, which Montgomery arithmetic doesn't care about)."""
    bias = jnp.asarray(_BIAS_ARR)
    return weak_carry(a + bias - b, 2)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


_R_MOD_Q_LIMBS = to_limbs(R_MOD_Q)           # fold weight of limb 48
_P385_LIMBS = to_limbs((1 << 385) % Q)       # fold weight of limb 47 bit 9+


def reduce_sum(x: jnp.ndarray) -> jnp.ndarray:
    """Tame a (..., 48) digit array with limbs <= ~2^14 and value <= ~2^390
    back to weak form (limbs <= ~2^9.03, value < 2^385-ish, same residue
    mod q). This is what makes multi-term sums of Montgomery elements —
    the Fq12 tower's anti-diagonal accumulations — safe inputs for the
    next conv: without it the top limb silently accumulates past the f32
    exactness bound (48 * 511^2 < 2^24) and every later product is wrong.

    Steps: widen by one limb, plain-carry (limb 48 absorbs the overflow),
    fold limb 48 back via 2^384 mod q, carry again (limb 47 absorbs),
    fold limb 47's excess beyond 9 bits via 2^385 mod q, one last carry.
    """
    x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, 1)])
    x = weak_carry(x, 3)   # limb 48 absorbs the whole overflow (value bound)
    spill = x[..., 48:49]
    x = x[..., :48] + spill * jnp.asarray(_R_MOD_Q_LIMBS)
    x = weak_carry(x, 2)   # limb 47 absorbs (~2^11); others < 2^9
    excess = x[..., 47] >> 9
    x = x.at[..., 47].set(x[..., 47] & 511)
    x = x + excess[..., None] * jnp.asarray(_P385_LIMBS)
    # Limb 47 may finish around 2^10.6; the conv exactness budget still
    # holds: 47*511^2 + 1540^2 = 14.7M < 2^24.
    return weak_carry(x, 1)


# ---------------------------------------------------------------------------
# Conversion / canonicalization
# ---------------------------------------------------------------------------

def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs -> Montgomery form (multiply by R^2 then REDC)."""
    r2 = jnp.broadcast_to(jnp.asarray(to_limbs(R2_MOD_Q)), a.shape)
    return mont_mul(a, r2)


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery weak form -> canonical limbs in [0, q)."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    x = mont_mul(a, one)          # == a * R^{-1} mod q, value < q + eps
    return _cond_sub_q(_ripple(x))


def _ripple(x: jnp.ndarray) -> jnp.ndarray:
    """Exact sequential carry to canonical byte digits (value must fit in
    48 limbs, i.e. < 2^384)."""
    limbs = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        t = x[..., i] + carry
        limbs.append(t & LIMB_MASK)
        carry = t >> LIMB_BITS
    return jnp.stack(limbs, axis=-1)


def _cond_sub_q(x: jnp.ndarray) -> jnp.ndarray:
    q_digits = jnp.asarray(_Q_LIMBS)
    limbs = []
    borrow = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        d = x[..., i] - q_digits[i] - borrow
        borrow = (d < 0).astype(jnp.int32)
        limbs.append(d + (borrow << LIMB_BITS))
    sub_res = jnp.stack(limbs, axis=-1)
    keep = (borrow > 0)[..., None]
    return jnp.where(keep, x, sub_res)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality of Montgomery weak forms."""
    return jnp.all(from_mont(a) == from_mont(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(from_mont(a) == 0, axis=-1)


# ---------------------------------------------------------------------------
# Exponentiation (for inversion and square roots; scan over constant bits)
# ---------------------------------------------------------------------------

def pow_windowed(x, exponent: int, mul, one, window: int = 4):
    """Generic left-to-right windowed exponentiation over a static python
    exponent via lax.scan; shared by Fq (here) and the Fq12 tower
    (ops/bls381.py). `mul` is the group law, `one` the identity element
    broadcast to x's shape."""
    assert exponent >= 0
    nbits = max(1, exponent.bit_length())
    nsteps = -(-nbits // window)
    digits = [(exponent >> (window * (nsteps - 1 - i))) & ((1 << window) - 1)
              for i in range(nsteps)]
    entries = [one, x]
    for _ in range(2, 1 << window):
        entries.append(mul(entries[-1], x))
    table = jnp.stack(entries)

    def body(acc, digit):
        for _ in range(window):
            acc = mul(acc, acc)
        return mul(acc, jnp.take(table, digit, axis=0)), None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(digits, dtype=jnp.int32))
    return acc


def pow_const(x: jnp.ndarray, exponent: int, window: int = 4) -> jnp.ndarray:
    """x^exponent in Montgomery form, static exponent, windowed scan."""
    one = jnp.broadcast_to(mont_constant(1), x.shape).astype(jnp.int32)
    return pow_windowed(x, exponent, mont_mul, one, window)


def inv(x: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse (0 -> 0), Montgomery form in and out."""
    return pow_const(x, Q - 2)


# ---------------------------------------------------------------------------
# Self-check (bench/deploy startup guard, like field25519.mul_selfcheck)
# ---------------------------------------------------------------------------

def mul_selfcheck(batch: int = 64, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    xs = [int(rng.integers(0, 2**62)) ** 7 % Q for _ in range(batch)]
    ys = [int(rng.integers(0, 2**62)) ** 7 % Q for _ in range(batch)]
    a = jnp.asarray(np.stack([to_limbs(x * R % Q) for x in xs]))
    b = jnp.asarray(np.stack([to_limbs(y * R % Q) for y in ys]))
    got = np.asarray(from_mont(mont_mul(a, b)))
    for i, (x, y) in enumerate(zip(xs, ys)):
        want = x * y % Q
        have = from_limbs(got[i])
        if have != want:
            raise AssertionError(
                f"field381 mont_mul mismatch at row {i}")

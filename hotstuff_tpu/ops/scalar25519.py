"""Arithmetic mod the Ed25519 group order L, batched, in JAX limb form.

The random-linear-combination (RLC) batch verification check
(ops/ed25519.verify_rlc_packed, crypto/eddsa.verify_batch_rlc) needs the
per-signature scalar products ``z_i * S_i mod L`` and ``z_i * k_i mod L``
and their sum computed ON DEVICE, next to the multi-scalar multiply that
consumes them — round-tripping 2n scalars through the host would put two
tunnel transfers in the middle of the one-dispatch verify program.

Representation: the same dense radix-2^8 int32 limb layout as
ops/field25519 — shape ``(..., 32)``, little-endian canonical bytes — so
scalars flow straight into the nibble-digit expansion the MSM windows use
(ops/ed25519.unpack_nibbles_msb).  Unlike the field module there is no
"weak" form here: every public entry point returns canonical bytes with
value in ``[0, L)``.

Reduction strategy: L = 2^252 + delta is not byte-aligned, so the
field-style fold-at-2^256 trick does not converge (2^256 mod L is itself
~2^252).  Instead multiplication reduces by **Montgomery reduction** at
R = 2^256, which is exactly byte-aligned: all intermediates stay
non-negative, truncation mod R and exact division by R are limb slicing,
and the whole thing is two schoolbook convolutions plus one exact carry
chain.  ``mul_mod_l`` composes two Montgomery multiplies (the second by
R^2 mod L) so callers never see the Montgomery domain.

The schoolbook products use the same depthwise-conv formulation as
field25519.mul: partial-product sums are < 32 * 255^2 < 2^21, exact in
float32, so the scalar path rides the MXU like the field path does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field25519 as F
from . import kern as _kern
from ..utils.intmath import L

NLIMBS = 32
LIMB_MASK = 0xFF

# delta = L - 2^252 (125 bits): why 4-bit window schedules over scalars
# reduced mod L are 64 windows, not 63 — L needs 253 bits.
DELTA = L - (1 << 252)

# Montgomery constants at R = 2^256.
R = 1 << 256
LPRIME = (-pow(L, -1, R)) % R      # -L^-1 mod R
R2 = (R * R) % L                   # to-Montgomery / fixup factor
R1 = R % L

_L_LIMBS = F.to_limbs(L)
_LPRIME_LIMBS = F.to_limbs(LPRIME)
_R2_LIMBS = F.to_limbs(R2)
# Shifted multiples for reducing a value < 2^256 ( < 16L ) to [0, L):
# 8L = 2^255 + 8*delta < 2^256 still fits 32 canonical bytes.
_L_MULTIPLES = [F.to_limbs(8 * L), F.to_limbs(4 * L),
                F.to_limbs(2 * L), F.to_limbs(L)]


# ---------------------------------------------------------------------------
# Host <-> limb conversion (python ints; not jitted) — shared layout with
# field25519, re-exported so scalar callers need one import.
# ---------------------------------------------------------------------------

to_limbs = F.to_limbs
from_limbs = F.from_limbs
batch_to_limbs = F.batch_to_limbs
batch_from_limbs = F.batch_from_limbs


# ---------------------------------------------------------------------------
# Exact limb plumbing (non-negative int32 coefficient vectors)
# ---------------------------------------------------------------------------

def _conv_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product of limb vectors: (..., Wa) x (..., Wb) ->
    (..., Wa+Wb-1) int32 coefficients (no reduction, no carrying).

    Same depthwise-conv shape as field25519.mul; inputs must be canonical
    bytes (< 2^8) so every coefficient sum stays < 32 * 255^2 < 2^21 —
    exact in float32 at the field module's measured precision setting.
    """
    wa, wb = a.shape[-1], b.shape[-1]
    batch_shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, (*batch_shape, wa))
    b = jnp.broadcast_to(b, (*batch_shape, wb))
    n = 1
    for d in batch_shape:
        n *= d
    lhs = a.reshape(1, n, wa).astype(jnp.float32)
    rhs = jnp.flip(b.reshape(n, 1, wb), -1).astype(jnp.float32)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(wb - 1, wb - 1)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=n,
        precision=F._PRECISION,
    ).reshape(*batch_shape, wa + wb - 1).astype(jnp.int32)
    return out


def _carry_bytes(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Exact ripple carry of non-negative int32 coefficients into ``width``
    canonical byte limbs (one unrolled sequential pass, like
    field25519._sequential_carry but width-generic and wrap-free).

    The represented value must fit in 8*width bits; the final carry out is
    dropped (callers size ``width`` so it is provably zero).
    """
    pad = width - x.shape[-1]
    if pad > 0:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    limbs = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(width):
        t = x[..., i] + carry
        limbs.append(t & LIMB_MASK)
        carry = t >> 8
    return jnp.stack(limbs, axis=-1)


def _cond_sub(x: jnp.ndarray, modulus_limbs: np.ndarray) -> jnp.ndarray:
    """If x >= m (x canonical 32 bytes), subtract m (borrow chain, like
    field25519._cond_sub_p but for an arbitrary 32-byte modulus)."""
    digits = jnp.asarray(modulus_limbs, dtype=jnp.int32)
    limbs = []
    borrow = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        d = x[..., i] - digits[i] - borrow
        borrow = (d < 0).astype(jnp.int32)
        limbs.append(d + (borrow << 8))
    sub_res = jnp.stack(limbs, axis=-1)
    keep = (borrow > 0)[..., None]  # borrow out => x < m => keep x
    return jnp.where(keep, x, sub_res)


def mod_small(x: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) canonical bytes, value < 2^256 (< 16L) -> value mod L.

    Four conditional subtractions of 8L, 4L, 2L, L — each multiple still
    fits 32 canonical bytes since 8L < 2^256."""
    for m in _L_MULTIPLES:
        x = _cond_sub(x, m)
    return x


# ---------------------------------------------------------------------------
# Montgomery multiplication at R = 2^256
# ---------------------------------------------------------------------------

def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b * R^-1 mod L for canonical byte-limb scalars.

    Valid whenever a * b < R*L (both inputs < L always qualifies; one
    input may range up to 2^256 - 1 if the other stays < L — the
    ``reduce512_mod_l`` high-half path uses that headroom).  Returns
    canonical bytes < L.

    Routed: ``HOTSTUFF_TPU_KERN=pallas`` dispatches the graftkern fused
    REDC kernel (ops/kern/scalar_mont), bit-identical to the lax
    reference below; ``mul_mod_l``/``reduce512_mod_l`` compose this
    primitive, so the route covers them too.
    """
    if _kern.use_pallas():
        return _kern.scalar_mont_mul(a, b)
    return _mont_mul_lax(a, b)


def _mont_mul_lax(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """The lax reference REDC (and the HOTSTUFF_TPU_KERN=lax route).

    REDC with byte-aligned R: T = a*b; m = (T mod R) * L' mod R;
    U = T + m*L is divisible by R, so U >> 256 is limb slicing after one
    exact carry chain; U < 2R*L makes a single conditional subtract
    enough.  Everything stays non-negative — no signed-limb handling.
    """
    t = _carry_bytes(_conv_mul(a, b), 64)          # T = a*b, canonical
    # m = (T mod R) * L' mod R: coefficients at index >= 32 carry weight
    # >= 2^256 == 0 (mod R), so they are dropped BEFORE the carry; the
    # final carry out of limb 31 is dropped for the same reason.
    m = _carry_bytes(_conv_mul(t[..., :32], jnp.asarray(_LPRIME_LIMBS))
                     [..., :32], 32)
    # U = T + m*L < R*L + R*L = 2R*L < 2^510: 64 canonical bytes.
    u = _conv_mul(m, jnp.asarray(_L_LIMBS))
    u = jnp.pad(u, [(0, 0)] * (u.ndim - 1) + [(0, 64 - u.shape[-1])]) + t
    u = _carry_bytes(u, 64)
    # U is an exact multiple of R: its low 32 canonical bytes are zero and
    # U/R = U >> 256 is the high slice; U < 2R*L => U >> 256 < 2L.
    return _cond_sub(u[..., 32:], _L_LIMBS)


def mul_mod_l(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod L for canonical byte-limb scalars (a*b < R*L; both < L
    always qualifies).  Two REDC passes: (abR^-1) then * R^2 * R^-1."""
    return mont_mul(mont_mul(a, b), jnp.asarray(_R2_LIMBS))


def add_mod_l(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b mod L for canonical scalars < L (sum < 2L < 2^254 fits 32
    bytes)."""
    return _cond_sub(_carry_bytes(a + b, NLIMBS), _L_LIMBS)


def reduce_limbsum_mod_l(s: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) int32 limb-wise sums of canonical scalars (limbs < 2^24,
    i.e. up to 2^16 summed terms) -> canonical value mod L.

    Value < 2^16 * L < 2^269 splits at the byte-aligned 2^256 boundary as
    hi*2^256 + lo with hi < 2^16, and hi*2^256 mod L == mont_mul(hi,
    R^2 mod L) — the same REDC primitive the products use.  The sharded
    verifier feeds this a psum of per-shard limb sums (limb-wise integer
    sums commute with the ICI reduction; the mod-L fold happens once,
    replicated)."""
    wide = _carry_bytes(s, 36)                     # < 2^269: 34 bytes + slack
    lo = wide[..., :32]
    hi = jnp.pad(wide[..., 32:],
                 [(0, 0)] * (wide.ndim - 1) + [(0, NLIMBS - 4)])
    return add_mod_l(mont_mul(hi, jnp.asarray(_R2_LIMBS)), mod_small(lo))


def sum_mod_l(u: jnp.ndarray, axis: int = -2) -> jnp.ndarray:
    """Sum of canonical scalars < L along ``axis``, mod L: limb-wise
    integer sum (n <= 4096 terms keep limbs < 2^20, far inside int32),
    then one fold through reduce_limbsum_mod_l."""
    return reduce_limbsum_mod_l(jnp.sum(u, axis=axis))


def add_small_multiple_of_l(x: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """x (..., 32) canonical < L  +  t (...,) int32 in [0, 8)  ->
    canonical 32 bytes of x + t*L  (< 8L < 2^256).

    The CRT lift to the full-group exponent 8L used by the RLC torsion
    handling (ops/ed25519.rlc_partials): x + t*L ≡ x (mod L) leaves the
    prime-order component untouched while choosing the scalar's mod-8
    residue, which is what the 8-torsion component of a point actually
    sees."""
    return _carry_bytes(x + t[..., None] * jnp.asarray(_L_LIMBS), NLIMBS)


def reduce512_mod_l(x: jnp.ndarray) -> jnp.ndarray:
    """(..., 64) canonical little-endian bytes (a 512-bit value) -> value
    mod L as canonical (..., 32) bytes.

    Split at 2^256: x = hi*2^256 + lo; hi < 2^256 rides the mont_mul
    headroom (hi * R2 < 2^256 * L), lo < 2^256 < 16L reduces by shifted
    conditional subtracts."""
    lo, hi = x[..., :32].astype(jnp.int32), x[..., 32:].astype(jnp.int32)
    return add_mod_l(mont_mul(hi, jnp.asarray(_R2_LIMBS)), mod_small(lo))

"""Backend probe for the graftkern Pallas layer.

``interpret_default()`` is THE one place the interpret/compiled decision
lives: every production kernel passes ``interpret=interpret_default()``
to its ``pallas_call`` so the choice follows the backend that actually
runs the program — Mosaic-compiled on a TPU, the Pallas interpreter
everywhere else (which is what keeps tier-1 CPU-runnable).  graftlint's
``pallas-interpret-in-prod`` rule (analysis/padshape.py) flags any
``interpret=True`` literal outside this module so a debug hack can
never pin a TPU deployment to the interpreter silently.
"""

from __future__ import annotations

import jax


def interpret_default() -> bool:
    """True when Pallas kernels must run under the interpreter.

    Read at TRACE time, never at import: ``jax.default_backend()``
    initializes the platform client, and importing the kern package must
    stay side-effect-free (same discipline as ops/ed25519._jit_donated —
    a second process probing the single-client tunneled TPU would
    otherwise fail at import)."""
    return jax.default_backend() != "tpu"


def interpret_probe() -> bool:
    """Run a one-tile kernel in FORCED interpreter mode and check the
    result — validates the interpreter itself (tests and kern_gate run
    this even on a machine with a TPU attached, where
    interpret_default() would say False)."""
    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def _k(x_ref, o_ref):
        o_ref[:] = x_ref[:] + 1

    out = pl.pallas_call(
        _k,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        # Deliberately forced: this probe validates the INTERPRETER,
        # independent of the backend; production kernels select via
        # interpret_default().
        # graftlint: disable=pallas-interpret-in-prod
        interpret=True,
    )(jnp.zeros((8, 128), jnp.int32))
    return bool((np.asarray(out) == 1).all())

"""graftkern kernel 1: fused GF(2^255-19) multiply.

One Pallas kernel fuses what the lax path spreads over a
conv_general_dilated launch plus five elementwise passes: the 32-limb
byte convolution, the wrap-38 fold, and the four parallel carry steps —
all on a carry-save accumulator that lives in the (rows, 128) padded
layout the whole time (fieldops module notes), so intermediate
coefficients never leave VMEM.  Batched over the row dimension: the
grid walks row blocks of up to fieldops.BLOCK_ROWS (multiples of the
8-sublane tile), one block per grid step.

Bit-identity: the kernel body is fieldops.f_mul, a transliteration of
field25519.mul with identical carry structure — pure int32, exact, so
outputs match the lax reference limb for limb (tests/test_kern.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import fieldops as FK
from .backend import interpret_default


def _field_mul_kernel(a_ref, b_ref, o_ref):
    o_ref[:] = FK.f_mul(a_ref[:], b_ref[:])


# jit-wrapped so the pallas trace is paid once per SHAPE, not once per
# call site — the verify program reaches this from hundreds of mul
# sites (see the kern package docstring for the measured difference).
@jax.jit
def _mul_rows(a_pad: jnp.ndarray, b_pad: jnp.ndarray) -> jnp.ndarray:
    rows = a_pad.shape[0]
    block, _ = FK.row_block(rows)
    return pl.pallas_call(
        _field_mul_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, FK.NLANES), jnp.int32),
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((block, FK.NLANES), lambda i: (i, 0))] * 2,
        out_specs=pl.BlockSpec((block, FK.NLANES), lambda i: (i, 0)),
        interpret=interpret_default(),
    )(a_pad, b_pad)


def field_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod p for weak (..., 32) int32 limb arrays — the Pallas
    route of field25519.mul (same signature, bit-identical result).
    Batch flattening / lane padding / row-block plumbing is the shared
    fieldops.launch_rows wrapper."""
    return FK.launch_rows(_mul_rows, a, b)

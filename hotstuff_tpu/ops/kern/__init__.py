"""graftkern: hand-laid Pallas kernels for the ed25519 verify hot path.

The lax-op modules (ops/field25519, ops/ed25519, ops/scalar25519) leave
XLA to schedule the limb arithmetic however it likes; this layer fuses
the three dominant primitives into Pallas kernels tuned to the VPU's
(8, 128) tile shape, behind the EXISTING public op signatures — the
scheduler / engine / sharding stack above is untouched, and the sharded
entries in parallel/sharded_verify.py route per-shard window sums
through the same kernels because they call the same ops:

  field_mul        the 32-limb byte convolution + wrap-38 parallel carry
                   of field25519.mul as ONE kernel, carry-save limbs in
                   a 128-lane vector, rows batched over sublanes
                   (ops/kern/field_mul.py).
  msm_window_accum the Straus inner loop — per-window 16-entry table
                   gather (one-hot masked sum) + the masked point-add
                   tree that dominates ed25519.msm_window_sums — fused
                   so window sums never round-trip through HBM between
                   limb ops (ops/kern/msm_accum.py).
  scalar_mont_mul  the mod-L Montgomery multiply (REDC at R = 2^256)
                   of scalar25519.mont_mul (ops/kern/scalar_mont.py).

Selection: ``HOTSTUFF_TPU_KERN=lax|pallas`` (read ONCE, at first use;
``set_mode`` re-pins it in-process and clears the jit caches so routed
programs re-trace).  The lax implementations stay in-tree as the
bit-identical reference and fallback — every kernel is property-tested
bit-identical against them (tests/test_kern.py), and the default stays
``lax`` until a real-device measurement re-pins it (bench.py's
``roofline`` headline is that measurement).

CPU story: each kernel selects ``interpret=`` off the backend at trace
time (ops/kern/backend.interpret_default) — on anything but a TPU the
kernels run through the Pallas interpreter, so tier-1 stays
CPU-runnable and the property sweeps exercise the exact kernel bodies a
TPU would compile.  Every pallas_call is wrapped in its own ``jax.jit``
so the per-call-site trace cost is paid once per shape, not once per
call site (~0.4 s/site -> ~4 ms/site measured; the verify program has
hundreds of mul sites).
"""

from __future__ import annotations

import os

_VALID_MODES = ("lax", "pallas")
_mode: str | None = None


def mode() -> str:
    """The kernel route, read ONCE from HOTSTUFF_TPU_KERN at first use
    (lazy, like the backend probe: importing this package must stay
    side-effect-free)."""
    global _mode
    if _mode is None:
        raw = os.environ.get("HOTSTUFF_TPU_KERN", "lax").strip().lower()
        m = raw or "lax"
        if m not in _VALID_MODES:
            raise ValueError(
                f"HOTSTUFF_TPU_KERN must be one of {_VALID_MODES}, "
                f"got {raw!r}")
        _mode = m
    return _mode


def use_pallas() -> bool:
    """True when the routed ops (field25519.mul, ed25519.msm_window_sums,
    scalar25519.mont_mul) should dispatch the Pallas kernels.  Read at
    TRACE time by the routers, so a cached jit keeps the route it was
    traced with — which is why set_mode clears the caches."""
    return mode() == "pallas"


def set_mode(m: str) -> None:
    """Re-pin the kernel route in-process (bench.py's roofline headline
    measures both routes from one process).  Clears the global jit
    caches: every routed program read use_pallas() at trace time, so a
    stale trace would keep dispatching the old route."""
    global _mode
    if m not in _VALID_MODES:
        raise ValueError(f"kern mode must be one of {_VALID_MODES}, "
                         f"got {m!r}")
    if m != mode():
        import jax

        _mode = m
        jax.clear_caches()


from .backend import interpret_default, interpret_probe  # noqa: E402
from .field_mul import field_mul  # noqa: E402
from .msm_accum import msm_window_accum  # noqa: E402
from .scalar_mont import scalar_mont_mul  # noqa: E402

__all__ = [
    "mode", "set_mode", "use_pallas",
    "interpret_default", "interpret_probe",
    "field_mul", "msm_window_accum", "scalar_mont_mul",
]

"""In-kernel GF(2^255-19) limb arithmetic for the graftkern Pallas layer.

Carry-save (8, 128)-tile layout: a field element is 32 radix-2^8 int32
limbs stored in lanes 0..31 of a 128-lane vector row (lanes 32..127
zero), rows batched over sublanes — the native VPU tile shape, so every
helper below is pure elementwise/roll work on full tiles.  The extra
lanes are not waste: the schoolbook product needs 63 coefficient slots,
so the carry-save accumulator lives in the SAME padded row as its
inputs and the whole multiply never changes layout.

Every function here is traced INSIDE a pallas kernel body and is a
bit-identical transliteration of the lax reference (ops/field25519):
same weak-normal form invariant (limbs < 2^9), same carry-step count
per op, pure int32 — so kernel outputs match the reference limb for
limb, which is what tests/test_kern.py's property sweeps assert.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utils.intmath import D, P

NLIMBS = 32
NLANES = 128
LIMB_MASK = 0xFF
K2D = (2 * D) % P


def limb_digits(x: int) -> list[int]:
    """Python int -> 32 canonical byte digits, little-endian (static
    python lists: pallas kernel bodies may not capture ARRAY constants,
    so constant rows are synthesized in-kernel via const_row)."""
    return [(x >> (8 * i)) & 0xFF for i in range(NLIMBS)]


# 8p bias for subtraction without negative intermediates — the same
# limb-dominating bias field25519.sub uses (every limb >= 1016 > any
# weak limb).
_SUB_BIAS_DIGITS = [8 * d for d in limb_digits(P)]
_K2D_DIGITS = limb_digits(K2D)


def lane_iota(shape) -> jnp.ndarray:
    """Per-lane index, broadcast over the leading dims (TPU needs >= 2-D
    iota; the padded rows always are)."""
    return jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)


def const_row(lane: jnp.ndarray, digits: list[int]) -> jnp.ndarray:
    """Broadcast a static limb vector into the padded-lane layout from
    scalar selects (pallas kernels cannot capture array constants; 32
    vector selects trace once per shape and cost nothing next to the
    conv's 32 MACs)."""
    x = jnp.zeros_like(lane)
    for i, d in enumerate(digits):
        if d:
            x = jnp.where(lane == i, d, x)
    return x


def carry_step(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry step on padded rows — field25519._carry_step
    in the 128-lane layout.  Every limb keeps its low byte; high bits
    move one lane up; the carry out of limb 31 wraps to lane 0 scaled by
    38 (2^256 === 38 mod p).  Lanes >= 32 are forced back to zero (the
    roll would otherwise leak limb 31's carry into lane 32)."""
    lane = lane_iota(x.shape)
    lo = x & LIMB_MASK
    hi = x >> 8
    wrapped = jnp.where(lane == 0,
                        jnp.roll(hi, 1 - NLIMBS, axis=-1) * 38,
                        jnp.roll(hi, 1, axis=-1))
    return jnp.where(lane < NLIMBS, lo + wrapped, 0)


def conv32(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product of two padded (rows, 128) limb rows:
    coefficient j lands in lane j (j = 0..62, zeros above).

    Formulation: per-row outer product, then ONE dot against a
    synthesized 0/1 anti-diagonal matrix (i + k == j) — the MXU form.
    A 32-step shifted-MAC loop computes the same thing on the VPU, but
    each of its rolls lowers to multiple HLO ops and XLA compile time
    explodes when the tree/window loops replicate the body (measured
    14x slower to compile); the dot keeps the kernel one op deep.  The
    select matrix is built in-kernel from iotas because pallas bodies
    may not capture array constants.

    Exactness: products < 2^18 and coefficient sums < 32 * (2^9)^2 =
    2^23 are exact in f32 at HIGHEST precision (same argument as the
    lax conv path; field25519.mul_selfcheck trips on any backend where
    that ever stops holding)."""
    ai = a[..., :NLIMBS]
    bi = b[..., :NLIMBS]
    outer = (ai[..., :, None] * bi[..., None, :]).astype(jnp.float32)
    outer = outer.reshape(*a.shape[:-1], NLIMBS * NLIMBS)
    i = jax.lax.broadcasted_iota(jnp.int32, (NLIMBS * NLIMBS, NLANES), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (NLIMBS * NLIMBS, NLANES), 1)
    antidiag = ((i // NLIMBS + i % NLIMBS) == j).astype(jnp.float32)
    return jnp.dot(outer, antidiag,
                   precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32).astype(jnp.int32)


def f_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod p, weak in / weak out — field25519.mul fused: one
    conv, the wrap-38 fold (lane j += 38 * lane j+32), four parallel
    carry steps.  Same op sequence, same carry counts: bit-identical."""
    lane = lane_iota(a.shape)
    acc = conv32(a, b)
    folded = acc + 38 * jnp.roll(acc, -NLIMBS, axis=-1)
    x = jnp.where(lane < NLIMBS, folded, 0)
    for _ in range(4):
        x = carry_step(x)
    return x


def f_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """field25519.add: one carry step restores limbs < 2^9."""
    return carry_step(a + b)


def f_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """field25519.sub: add the 8p bias, two carry steps."""
    x = a + const_row(lane_iota(a.shape), _SUB_BIAS_DIGITS) - b
    return carry_step(carry_step(x))


def f_neg(a: jnp.ndarray) -> jnp.ndarray:
    return f_sub(jnp.zeros_like(a), a)


# ---------------------------------------------------------------------------
# Point helpers (tuples of 4 padded coordinate rows: X, Y, Z, T ext /
# Y+X, Y-X, Z, 2dT cached) — transliterations of ed25519.to_cached_t /
# add_t, the exact op sequence the lax _tree_sum executes.
# ---------------------------------------------------------------------------


def to_cached(p):
    """(x, y, z, t) -> cached (y+x, y-x, z, 2d*t) — ed25519.to_cached_t."""
    x, y, z, t = p
    k2d = const_row(lane_iota(t.shape), _K2D_DIGITS)
    return (f_add(y, x), f_sub(y, x), z, f_mul(t, k2d))


def add_cached(p, qc):
    """Complete unified addition ext + cached -> ext (8 muls) —
    ed25519.add_t's separate-conv shape, op for op."""
    x1, y1, z1, t1 = p
    ypx2, ymx2, z2, t2d2 = qc
    a = f_mul(f_sub(y1, x1), ymx2)
    b = f_mul(f_add(y1, x1), ypx2)
    c = f_mul(t1, t2d2)
    zz = f_mul(z1, z2)
    d = f_add(zz, zz)
    e = f_sub(b, a)
    f = f_sub(d, c)
    g = f_add(d, c)
    h = f_add(b, a)
    return (f_mul(e, f), f_mul(g, h), f_mul(f, g), f_mul(e, h))


# ---------------------------------------------------------------------------
# Row-grid plumbing shared by the batched kernels
# ---------------------------------------------------------------------------

# Rows per grid block: 256 x 128 int32 = 128 KB per operand — three
# buffers plus the accumulator stay far inside the ~16 MB VMEM envelope
# while blocks stay multiples of the 8-sublane tile.
BLOCK_ROWS = 256


def row_block(n: int) -> tuple[int, int]:
    """Batch row count -> (block, padded_rows): block is the per-grid-
    step row count (multiple of 8, capped at BLOCK_ROWS), padded_rows
    the total the caller must pad to (a multiple of block)."""
    n8 = -(-max(n, 1) // 8) * 8
    block = min(BLOCK_ROWS, n8)
    return block, -(-n // block) * block


def launch_rows(launcher, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """The shared wrapper of the row-batched binary kernels (field_mul,
    scalar_mont_mul): broadcast the (..., 32) operands, flatten batch
    dims to rows, pad limbs into the 128-lane layout and rows to the
    grid block, hand the padded pair to ``launcher`` (a jitted
    pallas_call over (rows, 128) int32 inputs), and slice back."""
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, (*batch, NLIMBS))
    b = jnp.broadcast_to(b, (*batch, NLIMBS))
    n = 1
    for d in batch:
        n *= d
    if n == 0:
        return jnp.zeros((*batch, NLIMBS), jnp.int32)
    _, rows = row_block(n)
    pad = [(0, rows - n), (0, NLANES - NLIMBS)]
    out = launcher(
        jnp.pad(a.reshape(n, NLIMBS).astype(jnp.int32), pad),
        jnp.pad(b.reshape(n, NLIMBS).astype(jnp.int32), pad))
    return out[:n, :NLIMBS].reshape(*batch, NLIMBS)

"""graftkern kernel 2: the Straus MSM window accumulator.

The inner loop that dominates ed25519.msm_window_sums — per-window
16-entry table selection plus the masked binary-tree point-add fold
over the batch — fused into one kernel so a window's selected points,
cached forms and every tree level's intermediate limbs stay in VMEM:
the lax path round-trips each of those through XLA-scheduled buffers
between the gather and every point_add's eight conv launches.

Shape: ONE kernel invocation holds the whole per-point table and loops
the 64 MSB-first nibble windows with an in-kernel ``lax.fori_loop`` —
the loop body (selection + tree) traces once, and the table is read
into VMEM once for all 64 windows instead of once per window (the
grid-per-window form re-fetched it 64x AND unrolled the tree 64x into
the program, which priced the interpreter out of the CPU test lane).
Selection is a ONE-HOT MASKED SUM (exact for int32 limbs, and the
vector-friendly form — no gather unit dependency); identity table
entries make padding and digit-0 rows vanish without a separate mask,
the same trick as the lax path.

VMEM envelope: the table is B * 8 KB (8 MB at the B = 1024 launch cap)
— inside the ~16 MB budget with the output and tree temporaries, and
per-shard batches on the mesh path are far smaller.

Bit-identity: the tree replays ed25519._tree_sum's exact order
(point_add(pts[:m], to_cached(pts[m:])), halving) with the fieldops
transliterations of add_t/to_cached_t, so window sums match the lax
reference limb for limb.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import fieldops as FK
from .backend import interpret_default

_WINDOWS = 64
_TABLE = 16


def _msm_kernel(tab_ref, dig_ref, o_ref):
    b = tab_ref.shape[0]
    tab = tab_ref[:]                                       # (B, 16, 4, 32)
    digs = dig_ref[:]                                      # (B, 64)
    entry_iota = jax.lax.broadcasted_iota(jnp.int32, (b, _TABLE), 1)

    def window(j, carry):
        dig = jax.lax.dynamic_slice(digs, (0, j), (b, 1))[:, 0]
        onehot = (dig[:, None] == entry_iota).astype(jnp.int32)
        coords = []
        for c in range(4):
            sel = jnp.sum(tab[:, :, c, :] * onehot[:, :, None], axis=1)
            coords.append(
                jnp.pad(sel, [(0, 0), (0, FK.NLANES - FK.NLIMBS)]))
        pts = tuple(coords)
        m = b
        while m > 1:                                       # _tree_sum order
            m //= 2
            first = tuple(c[:m] for c in pts)
            second = tuple(c[m:] for c in pts)
            pts = FK.add_cached(first, FK.to_cached(second))
        for c in range(4):
            o_ref[j, c, :] = pts[c][0, :FK.NLIMBS]
        return carry

    jax.lax.fori_loop(0, _WINDOWS, window, 0)


# jit-wrapped: one pallas trace per (B,) shape (kern package docstring).
@jax.jit
def _accum(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    return pl.pallas_call(
        _msm_kernel,
        out_shape=jax.ShapeDtypeStruct((_WINDOWS, 4, FK.NLIMBS),
                                       jnp.int32),
        interpret=interpret_default(),
    )(table, digits)


def msm_window_accum(table: jnp.ndarray,
                     digits: jnp.ndarray) -> jnp.ndarray:
    """Per-window Straus sums from a prebuilt table — the Pallas route
    of the selection + tree half of ed25519.msm_window_sums.

    Args:
      table:  (B, 16, 4, 32) int32 ext tables (ed25519.msm_table; entry
              0 is the identity, so padding/excluded rows select it).
      digits: (B, 64) int32 MSB-first 4-bit windows.  B must be a power
              of two (msm_window_sums pads before calling).
    Returns:
      (64, 4, 32) int32 MSB-first window sums, bit-identical to the lax
      chunked-scan path.
    """
    b = table.shape[0]
    if b < 1 or b & (b - 1):
        raise ValueError(
            f"msm_window_accum batch must be a power of two, got {b}")
    return _accum(jnp.asarray(table, jnp.int32),
                  jnp.asarray(digits, jnp.int32))

"""graftkern kernel 3: the mod-L Montgomery multiply.

scalar25519.mont_mul — REDC at the byte-aligned R = 2^256 — as one
fused kernel: the two schoolbook convolutions (a*b and m*L), the m =
T * L' mod R fold, both exact ripple-carry chains and the final
conditional subtract all happen on carry-save rows in VMEM; the lax
path runs them as separate conv launches with XLA-scheduled buffers in
between.  This is the scalar half of the RLC check (z_i * S_i and
z_i * k_i mod L next to the MSM that consumes them); reduce512_mod_l
and mul_mod_l compose this same primitive, so routing mont_mul covers
them.

Bit-identity: same intermediate widths, same carry chains (exact ripple
unrolled per limb, final carries dropped exactly where the lax code
proves them zero), same single conditional subtract — outputs match
scalar25519's Montgomery product byte for byte (tests/test_kern.py,
including the one-input-up-to-2^256 headroom path reduce512 rides).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...utils.intmath import L
from . import fieldops as FK
from .backend import interpret_default

R = 1 << 256
LPRIME = (-pow(L, -1, R)) % R

_L_DIGITS = FK.limb_digits(L)
_LPRIME_DIGITS = FK.limb_digits(LPRIME)


def _carry_bytes(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Exact ripple carry of non-negative int32 coefficient lanes into
    ``width`` canonical byte lanes (scalar25519._carry_bytes, unrolled
    per limb on vector rows; the final carry out is dropped — callers
    size ``width`` so it is provably zero)."""
    carry = jnp.zeros_like(x[..., 0])
    outs = []
    for i in range(width):
        t = x[..., i] + carry
        outs.append(t & 0xFF)
        carry = t >> 8
    out = jnp.stack(outs, axis=-1)
    return jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                   + [(0, FK.NLANES - width)])


def _cond_sub_l(x: jnp.ndarray) -> jnp.ndarray:
    """If x >= L (x canonical bytes in lanes 0..31), subtract L —
    scalar25519._cond_sub's borrow chain, unrolled per limb."""
    borrow = jnp.zeros_like(x[..., 0])
    outs = []
    for i in range(FK.NLIMBS):
        d = x[..., i] - _L_DIGITS[i] - borrow
        borrow = (d < 0).astype(jnp.int32)
        outs.append(d + (borrow << 8))
    sub_res = jnp.stack(outs, axis=-1)
    sub_res = jnp.pad(sub_res, [(0, 0)] * (sub_res.ndim - 1)
                      + [(0, FK.NLANES - FK.NLIMBS)])
    keep = (borrow > 0)[..., None]  # borrow out => x < L => keep x
    return jnp.where(keep, x, sub_res)


def _mont_kernel(a_ref, b_ref, o_ref):
    a = a_ref[:]
    b = b_ref[:]
    lane = FK.lane_iota(a.shape)
    l_row = FK.const_row(lane, _L_DIGITS)
    lprime_row = FK.const_row(lane, _LPRIME_DIGITS)
    # T = a * b, canonical 64 bytes.
    t = _carry_bytes(FK.conv32(a, b), 64)
    # m = (T mod R) * L' mod R: coefficients at lane >= 32 carry weight
    # >= 2^256 == 0 (mod R) — dropped BEFORE the carry, like the lax
    # slice; the carry's own final out is dropped for the same reason.
    t_lo = jnp.where(lane < FK.NLIMBS, t, 0)
    m_coeffs = FK.conv32(t_lo, lprime_row)
    m = _carry_bytes(jnp.where(lane < FK.NLIMBS, m_coeffs, 0), FK.NLIMBS)
    # U = T + m*L < 2RL: 64 canonical bytes; U/R is the high lane slice.
    u = _carry_bytes(FK.conv32(m, l_row) + t, 64)
    hi = jnp.pad(u[..., FK.NLIMBS:64],
                 [(0, 0)] * (u.ndim - 1) + [(0, FK.NLANES - FK.NLIMBS)])
    o_ref[:] = _cond_sub_l(hi)


# jit-wrapped: one pallas trace per shape (kern package docstring).
@jax.jit
def _mont_rows(a_pad: jnp.ndarray, b_pad: jnp.ndarray) -> jnp.ndarray:
    rows = a_pad.shape[0]
    block, _ = FK.row_block(rows)
    return pl.pallas_call(
        _mont_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, FK.NLANES), jnp.int32),
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((block, FK.NLANES), lambda i: (i, 0))] * 2,
        out_specs=pl.BlockSpec((block, FK.NLANES), lambda i: (i, 0)),
        interpret=interpret_default(),
    )(a_pad, b_pad)


def scalar_mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b * R^-1 mod L for canonical (..., 32) byte-limb scalars —
    the Pallas route of scalar25519.mont_mul (same signature and
    headroom contract: a*b < R*L, so one input may range to 2^256 - 1
    when the other stays < L).  Returns canonical bytes < L.  Batch
    flattening / lane padding / row-block plumbing is the shared
    fieldops.launch_rows wrapper."""
    return FK.launch_rows(_mont_rows, a, b)

"""GF(2^255 - 19) with radix-2^5 limbs and an int8 depthwise-conv multiply.

PROFILE.md's #1 remaining lever: the production engine (field25519.py)
multiplies 32 radix-2^8 limbs through a float32 depthwise convolution —
exact because partial-product sums stay under 2^23, but every f32 MXU
pass costs bf16x3 emulation.  This module re-limbs the field so the same
convolution can feed the MXU's native int8 pipeline.

Radix choice (why 2^5 and not the 2^7 first guess): an int8-strict weak
form needs every limb to re-enter [0, 127] after finitely many parallel
carry steps, and the carry out of the top limb wraps to limb 0 scaled by
2^(b*N) mod p.  For radix 2^7 (37 limbs, 259 bits) that scale is
19 * 2^4 = 304 >= 2^7, so limb 0 plateaus at ~127 + 304 and NEVER fits
int8 — the uniform-radix-2^7 design is unimplementable.  Radix 2^5 tiles
255 = 5 * 51 exactly, making the wrap scale exactly 19 < 2^5: interval
analysis shows five carry steps take post-multiply coefficients
(< 2^22) to limbs <= 31 + 19 = 50.

* 51 limbs of 5 bits, weak invariant limbs <= 63 (mul outputs satisfy
  <= 50); every weak limb is a lossless int8 cast.
* The (1, n, 51) x (n, 1, 51) depthwise conv accumulates in int32
  (preferred_element_type): partial-product sums <= 51 * 63^2 < 2^18,
  exact by integer arithmetic — no precision knob, unlike the f32 path.
* Post-fold coefficients < 2^22 (int32-safe); five parallel carry steps
  restore the weak form.

The open question — why this is an A/B and not the default — is whether
XLA's int8 conv at feature_group_count ~1024 beats the f32 path on a
real chip with 2.5x the MACs (51^2 vs 32^2 taps).
scripts/ab_int8_mul.py measures both engines' mul-chain slopes;
PROFILE.md records the verdict.

Reference parity: same workload as field25519.py (the limb substrate of
crypto/src/lib.rs:210-223 batch verification).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NLIMBS = 51
LIMB_BITS = 5
LIMB_MASK = (1 << LIMB_BITS) - 1
P = 2**255 - 19

# 2^(5*51) = 2^255 ≡ 19 (mod p): the wrap scale that makes int8-strict
# weak normalization possible at all (see module docstring).
_WRAP = (1 << (LIMB_BITS * NLIMBS)) % P
assert _WRAP == 19


def to_limbs(x: int) -> np.ndarray:
    """Python int -> (51,) int32 canonical 5-bit limbs."""
    x = int(x) % (1 << (LIMB_BITS * NLIMBS))
    return np.array([(x >> (LIMB_BITS * i)) & LIMB_MASK
                     for i in range(NLIMBS)], dtype=np.int32)


def from_limbs(limbs) -> int:
    limbs = np.asarray(limbs).reshape(NLIMBS)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(limbs))


def batch_to_limbs(xs) -> np.ndarray:
    return np.stack([to_limbs(x) for x in xs])


def batch_from_limbs(arr) -> list:
    return [from_limbs(row) for row in np.asarray(arr)]


def _carry_step(x: jnp.ndarray) -> jnp.ndarray:
    """Keep 5 low bits, pass the rest one limb up; the top limb's carry
    wraps to limb 0 scaled by 19.  Value preserved mod p."""
    lo = x & LIMB_MASK
    hi = x >> LIMB_BITS
    wrapped = jnp.roll(hi, 1, axis=-1)
    scale = jnp.ones((NLIMBS,), dtype=jnp.int32).at[0].set(_WRAP)
    return lo + wrapped * scale


def weak_normalize(x: jnp.ndarray, steps: int) -> jnp.ndarray:
    for _ in range(steps):
        x = _carry_step(x)
    return x


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod p (weak in, weak out) via an INT8 depthwise convolution.

    Inputs must satisfy the weak invariant (limbs <= 63): cast to int8 is
    lossless.  int32 accumulation makes the product exact by
    construction.  Five carry steps restore limbs <= 50."""
    batch_shape = a.shape[:-1]
    n = 1
    for d in batch_shape:
        n *= d
    lhs = a.reshape(1, n, NLIMBS).astype(jnp.int8)
    rhs = jnp.flip(b.reshape(n, 1, NLIMBS), -1).astype(jnp.int8)
    coeffs = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(NLIMBS - 1, NLIMBS - 1)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=n,
        preferred_element_type=jnp.int32,
    ).reshape(*batch_shape, 2 * NLIMBS - 1)
    lo, hi = coeffs[..., :NLIMBS], coeffs[..., NLIMBS:]
    folded = lo + _WRAP * jnp.pad(hi, [(0, 0)] * (hi.ndim - 1) + [(0, 1)])
    return weak_normalize(folded, 5)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def _sequential_carry(x: jnp.ndarray):
    limbs = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        t = x[..., i] + carry
        limbs.append(t & LIMB_MASK)
        carry = t >> LIMB_BITS
    return jnp.stack(limbs, axis=-1), carry


_P_DIGITS = [(P >> (LIMB_BITS * i)) & LIMB_MASK for i in range(NLIMBS)]


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    p_digits = jnp.asarray(_P_DIGITS, dtype=jnp.int32)
    limbs = []
    borrow = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        d = x[..., i] - p_digits[i] - borrow
        borrow = (d < 0).astype(jnp.int32)
        limbs.append(d + (borrow << LIMB_BITS))
    sub_res = jnp.stack(limbs, axis=-1)
    keep = (borrow > 0)[..., None]
    return jnp.where(keep, x, sub_res)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Weak element -> canonical limbs (5-bit, value in [0, p))."""
    x, carry = _sequential_carry(x)
    x = x.at[..., 0].add(_WRAP * carry)
    x, carry = _sequential_carry(x)
    x = x.at[..., 0].add(_WRAP * carry)
    x = _cond_sub_p(x)
    return _cond_sub_p(x)


def mul_selfcheck(batch: int = 256, seed: int = 0) -> None:
    """Exactness proof on the CURRENT backend over adversarial weak limbs
    (all-63 rows included).  Integer arithmetic end to end, so a failure
    means the backend's int8 conv itself is broken."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 64, (batch, NLIMBS))
    b = rng.integers(0, 64, (batch, NLIMBS))
    a[0, :] = 63
    b[0, :] = 63
    got = batch_from_limbs(np.asarray(
        canonical(mul(jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32)))))
    want = [(x * y) % P for x, y in zip(batch_from_limbs(a),
                                        batch_from_limbs(b))]
    if got != want:
        raise AssertionError("int8 radix-2^5 multiply is not exact "
                             "on this backend")

"""Device-side cryptographic kernels (the JAX/TPU compute substrate).

Modules:
  field25519    GF(2^255-19) limb arithmetic (radix-2^8 int32 limbs; the
                schoolbook product is a depthwise conv on the MXU).
  scalar25519   Arithmetic mod the Ed25519 group order L (Montgomery
                reduction at the byte-aligned R = 2^256) — the scalar
                half of the RLC batch check.
  ed25519       Curve ops and the two batch-verification programs:
                per-signature (comb + windowed ladder per vote) and the
                random-linear-combination (RLC) one-MSM path.
  field381 / bls381   BLS12-381 field + pairing kernels (QC aggregate
                verification under scheme=bls).

The RLC check in one paragraph: per-signature verification proves
[S_i]B == R_i + [k_i]A_i once per vote.  Drawing coefficients z_i from a
deterministic PRF over the batch content and summing z_i*(eq_i) collapses
a quorum to ONE equation, [sum z_i S_i]B == sum [z_i]R_i + [z_i k_i]A_i,
whose variable half is a single 2n-point multi-scalar multiplication
(Straus shared 4-bit windows + a masked binary-tree batch reduction —
see ops/ed25519.msm_window_sums).  All-valid batches — the steady state
of quorum-certificate verification — pay one MSM instead of 2n ladders;
a failed combined check bisects down to the per-signature path, so a bad
vote is still pinpointed and the returned mask is bit-identical to
verify_batch's.  Coefficients must be >= 128 bits: an adversary who can
cancel a defect against the z-weighted sum forges a batch verdict, and
the cancellation probability is 2^-(coefficient bits) — shorter
coefficients would make the combined check the system's weakest link,
below the curve's ~2^126 security level.

Torsion handling: E(Fp) is Z/8 x Z/L, and a scalar acts mod 8 on a
point's 8-torsion component — so the MSM scalars are CRT-lifted to the
full-group exponent 8L (ops/scalar25519.add_small_multiple_of_l) so that
every row's torsion defect enters the combined sum with exactly the
coefficient the per-signature cofactorless equation uses.  A single
defective row (including any mixed-order A or R an adversary crafts —
small-order points are already rejected host-side) therefore passes or
fails the combined check exactly as verify_batch would.  Known residual:
two or more colluding rows whose 8-torsion defects cancel exactly can
make the combined check accept where per-signature verification rejects
each row — inherent to any deterministic-coefficient cofactorless batch
check (cf. Chalkias et al., "Taming the many EdDSAs"); committees that
must exclude it should subgroup-check authority keys at registration
([L]A == identity, one-time per key).
"""

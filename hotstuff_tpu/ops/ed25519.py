"""Ed25519 curve operations and batched signature verification on TPU.

The device-side half of the framework's equivalent of the reference's
``Signature::verify`` / ``Signature::verify_batch``
(reference: crypto/src/lib.rs:177-224).  Scalars, hashing (SHA-512) and
encoding checks live on the host (see hotstuff_tpu/crypto/eddsa.py); the
device receives raw scalar/point bytes and returns a per-signature
validity mask — the mask shape is what quorum-certificate verification
consumes (consensus/src/messages.rs:180-198 in the reference).

The check [S]B - [k]A == R splits into a fixed-base comb for [S]B (32
adds against a host-precomputed affine table, zero doublings) plus a
4-bit windowed variable-base ladder for [k](-A) (64 scan steps of four
doublings and one add against an on-device 16-entry table). See
scripts/PROFILE.md for the measurements behind this shape.

TPU-first design notes:
* Points are dense ``(..., 4, 32)`` int32 arrays (X, Y, Z, T) in extended
  twisted-Edwards coordinates — a pytree-free layout that vmaps/shards
  cleanly along the batch axis.
* All control flow is static: complete addition formulas (no exceptional
  cases), `lax.scan` over fixed digit schedules, table selection via
  `take_along_axis` (gather on device).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field25519 as F
from ..utils.intmath import BX, BY, D, L, P, SQRT_M1, next_pow2

K2D = (2 * D) % P

_const = F.constant

# A/B switches for the point-op conv shapes (see scripts/eval_device.py).
# Defaults are the slope-measured winners on a real v5e chip.
import os as _os

_STACK_MULS = _os.environ.get("HOTSTUFF_TPU_STACK_MULS", "0") == "1"
_ONEHOT_SELECT = _os.environ.get("HOTSTUFF_TPU_ONEHOT_SELECT", "0") == "1"
_JOINT_DECOMPRESS = _os.environ.get("HOTSTUFF_TPU_JOINT_DECOMPRESS", "1") == "1"
# Carry point coordinates through the ladder/comb scans as a 4-tuple of
# (B, 32) arrays instead of one stacked (B, 4, 32) array. Hypothesis was
# that _pack/_unpack in the scan body cost real data movement; measured on
# a v5e the packed layout is consistently ~1-2 ms/batch FASTER (XLA fuses
# the packing; the stacked table gather beats 4 per-coordinate gathers),
# so the default stays packed.
_TUPLE_POINTS = _os.environ.get("HOTSTUFF_TPU_TUPLE_POINTS", "0") == "1"


# ---------------------------------------------------------------------------
# Point representation helpers.  ext = (X, Y, Z, T); cached = (Y+X, Y-X, Z, 2dT)
# ---------------------------------------------------------------------------

_EXT_X, _EXT_Y, _EXT_Z, _EXT_T = range(4)


def _pack(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=-2)


def _unpack(p):
    return p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]


def identity_ext(batch_shape=()) -> jnp.ndarray:
    one, zero = _const(1), _const(0)
    pt = _pack(zero, one, one, zero)
    return jnp.broadcast_to(pt, (*batch_shape, 4, F.NLIMBS))


def basepoint_ext() -> jnp.ndarray:
    return _pack(_const(BX), _const(BY), _const(1), _const(BX * BY % P))


def to_cached(p: jnp.ndarray) -> jnp.ndarray:
    return _pack(*to_cached_t(_unpack(p)))


def cached_neg(c: jnp.ndarray) -> jnp.ndarray:
    """cached(P) -> cached(-P): swap (Y+X, Y-X), negate 2dT."""
    ypx, ymx, z, t2d = _unpack(c)
    return _pack(ymx, ypx, z, F.neg(t2d))


def point_add(p: jnp.ndarray, qc: jnp.ndarray) -> jnp.ndarray:
    """Complete unified addition, ext + cached -> ext (8 field muls).

    add-2008-hwcd-3 for a=-1 (the ref10 ge_add shape) — complete on the
    twisted Edwards curve, so it needs no doubling/identity branches: ideal
    for SIMD/scan execution on TPU.  Default: the muls stay separate
    batch-group convs, which XLA overlaps well.  HOTSTUFF_TPU_STACK_MULS=1
    instead fuses the 4 independent input products and the 4 output
    products into two 4*batch-group convs — slope-measured ~2x SLOWER
    end-to-end on a v5e (scripts/PROFILE.md), kept only as an A/B switch
    for future backends.
    """
    if not _STACK_MULS:
        return _pack(*add_t(_unpack(p), _unpack(qc)))
    x1, y1, z1, t1 = _unpack(p)
    ypx2, ymx2, z2, t2d2 = _unpack(qc)
    m = F.mul(_pack(F.sub(y1, x1), F.add(y1, x1), t1, z1),
              _pack(ymx2, ypx2, t2d2, z2))
    a, b, c, zz = _unpack(m)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return F.mul(_pack(e, g, f, e), _pack(f, h, g, h))


def point_dbl(p: jnp.ndarray, with_t: bool = True) -> jnp.ndarray:
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4M + 4S.

    with_t=False skips the T-output multiply (3M + 4S): legal whenever the
    next consumer is another doubling, which only reads X, Y, Z.  Static
    python bool, so each variant compiles to its own fixed program.
    Default: separate batch-group convs (XLA overlaps the 4 independent
    squarings); HOTSTUFF_TPU_STACK_MULS=1 fuses them into stacked convs —
    measured slower (see point_add).
    """
    x1, y1, z1, _ = _unpack(p)
    if not _STACK_MULS:
        out = dbl_t((x1, y1, z1), with_t=with_t)
        if with_t:
            return _pack(*out)
        return _pack(*out, jnp.zeros_like(x1))
    s = F.sqr(_pack(x1, y1, z1, F.add(x1, y1)))
    a, b, zz, s3 = _unpack(s)
    c = F.add(zz, zz)
    e = F.sub(F.sub(s3, a), b)                      # 2*X1*Y1
    g = F.sub(b, a)                                 # B - A   (= D + B, D = -A)
    f = F.sub(g, c)
    h = F.neg(F.add(a, b))                          # -(A+B)  (= D - B)
    if with_t:
        return F.mul(_pack(e, g, f, e), _pack(f, h, g, h))
    out = F.mul(jnp.stack([e, g, f], axis=-2),
                jnp.stack([f, h, g], axis=-2))
    t_zero = jnp.zeros_like(out[..., :1, :])
    return jnp.concatenate([out, t_zero], axis=-2)


# ---------------------------------------------------------------------------
# Decompression (x-recovery), fully on device
# ---------------------------------------------------------------------------

def decompress_t(y_limbs: jnp.ndarray, sign_bit: jnp.ndarray):
    """(..., 32) canonical y limbs + (...,) sign bit ->
    ((x, y, z, t) tuple, ok mask).

    RFC 8032 §5.1.3 x-recovery: x = u v^3 (u v^7)^((p-5)/8), with u = y²-1,
    v = d y²+1; multiply by sqrt(-1) when v x² = -u; fail when neither.
    The (p-5)/8 power runs as a scan over a constant bit schedule.
    """
    one = jnp.broadcast_to(_const(1), y_limbs.shape)
    dd = jnp.broadcast_to(_const(D), y_limbs.shape)
    y2 = F.sqr(y_limbs)
    u = F.sub(y2, one)
    v = F.add(F.mul(dd, y2), one)
    v3 = F.mul(F.sqr(v), v)
    v7 = F.mul(F.sqr(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    vxx = F.mul(v, F.sqr(x))
    ok_direct = F.eq(vxx, u)
    ok_twist = F.eq(vxx, F.neg(u))
    x = jnp.where(ok_twist[..., None],
                  F.mul(x, jnp.broadcast_to(_const(SQRT_M1), x.shape)), x)
    ok = ok_direct | ok_twist
    # sign adjustment; x == 0 with sign 1 is invalid
    x_zero = F.is_zero(x)
    flip = (F.parity(x) != sign_bit) & ~x_zero
    x = jnp.where(flip[..., None], F.neg(x), x)
    ok = ok & ~(x_zero & (sign_bit == 1))
    t = F.mul(x, y_limbs)
    z = jnp.broadcast_to(_const(1), y_limbs.shape)
    return (x, y_limbs, z, t), ok


def decompress(y_limbs: jnp.ndarray, sign_bit: jnp.ndarray):
    """Packed-layout wrapper over decompress_t: -> ((..., 4, 32) ext, ok)."""
    (x, y, z, t), ok = decompress_t(y_limbs, sign_bit)
    return _pack(x, y, z, t), ok


# ---------------------------------------------------------------------------
# Tuple-layout point ops (the scan-hot-loop form; see _TUPLE_POINTS)
# ---------------------------------------------------------------------------

def identity_t(batch_shape=()):
    one = jnp.broadcast_to(_const(1), (*batch_shape, F.NLIMBS))
    zero = jnp.broadcast_to(_const(0), (*batch_shape, F.NLIMBS))
    return (zero, one, one, zero)


def to_cached_t(p):
    """(x, y, z, t) -> cached (y+x, y-x, z, 2d*t)."""
    x, y, z, t = p
    k2d = jnp.broadcast_to(_const(K2D), t.shape)
    return (F.add(y, x), F.sub(y, x), z, F.mul(t, k2d))


def add_t(p, qc):
    """Complete unified addition on tuples: ext + cached -> ext (8 muls,
    separate batch-group convs — the measured-best conv shape)."""
    x1, y1, z1, t1 = p
    ypx2, ymx2, z2, t2d2 = qc
    a = F.mul(F.sub(y1, x1), ymx2)
    b = F.mul(F.add(y1, x1), ypx2)
    c = F.mul(t1, t2d2)
    zz = F.mul(z1, z2)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def dbl_t(p, with_t: bool = True):
    """Doubling on tuples (dbl-2008-hwcd, a=-1): 4M+4S (3M+4S w/o T).

    Accepts a 3-tuple (x, y, z) or 4-tuple (T input unused); returns a
    3-tuple when with_t=False."""
    x1, y1, z1 = p[0], p[1], p[2]
    a = F.sqr(x1)
    b = F.sqr(y1)
    zz = F.sqr(z1)
    c = F.add(zz, zz)
    e = F.sub(F.sub(F.sqr(F.add(x1, y1)), a), b)   # 2*X1*Y1
    g = F.sub(b, a)
    f = F.sub(g, c)
    h = F.neg(F.add(a, b))
    if with_t:
        return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g))


# ---------------------------------------------------------------------------
# Fixed-base comb table for S*B (host-precomputed, device constant)
# ---------------------------------------------------------------------------

_COMB_W = 8          # one comb position per S byte
_COMB_POSITIONS = 32

_comb_cache: np.ndarray | None = None


def _host_pt_add(p, q):
    """Extended-coordinate add on python ints (table generation only)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def comb_table() -> np.ndarray:
    """(32, 256, 4, 32) int32: COMB[j][d] = cached affine form of d*(256^j)*B.

    S*B = sum_j COMB[j][S_byte_j] — 31 additions and ZERO doublings for the
    whole fixed-base half of the verification equation (the little-endian S
    bytes are directly the comb digits). Built lazily on host (~8k python
    point adds + one batched inversion), then baked into the jitted program
    as a constant (~4 MB).
    """
    global _comb_cache
    if _comb_cache is not None:
        return _comb_cache
    base = (BX, BY, 1, BX * BY % P)
    entries = []  # flat ext points, position-major
    for _ in range(_COMB_POSITIONS):
        acc = (0, 1, 1, 0)
        for _ in range(256):
            entries.append(acc)
            acc = _host_pt_add(acc, base)
        base = acc  # 256^{j+1} * B = 256 * (256^j * B); acc ran to 256*base
    # Batch affine normalization: one modular inverse total (Montgomery).
    zs = [e[2] for e in entries]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    invs = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        invs[i] = prefix[i] * inv_all % P
        inv_all = inv_all * zs[i] % P
    out = np.zeros((_COMB_POSITIONS, 256, 4, F.NLIMBS), np.int32)
    for idx, ((x, y, _, _), zi) in enumerate(zip(entries, invs)):
        xa, ya = x * zi % P, y * zi % P
        j, d = divmod(idx, 256)
        out[j, d, 0] = F.to_limbs((ya + xa) % P)
        out[j, d, 1] = F.to_limbs((ya - xa) % P)
        out[j, d, 2] = F.to_limbs(1)
        out[j, d, 3] = F.to_limbs(K2D * xa * ya % P)
    _comb_cache = out
    return out


# ---------------------------------------------------------------------------
# Batched verification
# ---------------------------------------------------------------------------

def _digit_select(table: jnp.ndarray, digit: jnp.ndarray) -> jnp.ndarray:
    """table (..., Ktab, 4coord, 32), digit (...,) in [0,K) -> (..., 4, 32).

    Default: take_along_axis (XLA gather).  HOTSTUFF_TPU_ONEHOT_SELECT=1
    switches to a one-hot masked sum, which looked 4x better in an isolated
    microbench but is neutral-to-worse inside the full verify program on a
    v5e (scripts/PROFILE.md) — kept as an A/B switch.
    """
    if not _ONEHOT_SELECT:
        idx = digit[..., None, None, None].astype(jnp.int32)
        return jnp.take_along_axis(table, idx, axis=-3)[..., 0, :, :]
    k = table.shape[-3]
    d = jax.lax.broadcasted_iota(jnp.int32, (k,), 0)
    mask = (digit[..., None] == d).astype(table.dtype)[..., None, None]
    return jnp.sum(table * mask, axis=-3)




def unpack_nibbles_msb(k_bytes: jnp.ndarray) -> jnp.ndarray:
    """(B, 32) uint8 little-endian scalar -> (B, 64) int32 MSB-first 4-bit
    digits, the schedule of the windowed variable-base ladder.

    Runs on device: the host ships raw scalar bytes; digit expansion is
    free next to the curve arithmetic.
    """
    b = k_bytes.astype(jnp.int32)[..., ::-1]  # big-endian byte order
    hi, lo = b >> 4, b & 0xF
    return jnp.stack([hi, lo], axis=-1).reshape(*b.shape[:-1], 64)


def split_y_sign(y_bytes: jnp.ndarray):
    """(B, 32) uint8 compressed point -> ((B, 32) int32 y limbs with bit
    255 cleared, (B,) int32 x-sign bit). Device-side byte parsing."""
    y = y_bytes.astype(jnp.int32)
    sign = y[..., 31] >> 7
    y = y.at[..., 31].set(y[..., 31] & 0x7F)
    return y, sign


def verify_compact(a_bytes: jnp.ndarray, r_bytes: jnp.ndarray,
                   s_bytes: jnp.ndarray, k_bytes: jnp.ndarray) -> jnp.ndarray:
    """Device-side Ed25519 verification from raw wire bytes.

    Args (all (B, 32) uint8): compressed pubkey A, compressed R, scalar S
    (little-endian), and the host-hashed challenge k = SHA512(R||A||M) mod L.
    130 bytes/signature cross the host->device boundary; limb conversion,
    sign extraction and digit expansion all happen on device.

    Returns (B,) bool validity mask (host-side canonicality checks are
    ANDed by the caller, crypto/eddsa.verify_batch).
    """
    ay, a_sign = split_y_sign(a_bytes)
    ry, r_sign = split_y_sign(r_bytes)
    s_digits = s_bytes.astype(jnp.int32)  # little-endian bytes = comb digits
    k_digits = unpack_nibbles_msb(k_bytes)
    return verify_prepared(ay, a_sign, ry, r_sign, s_digits, k_digits)


def _jit_donated(fn):
    """jit with arg 0 donated: the production verify loop hands each
    packed buffer to the device exactly once, so XLA may reuse its memory
    for temporaries — which matters on the tunneled chip, where buffers
    otherwise pile up behind the slow fetch path.  Donation is
    unimplemented on CPU (it would only emit a warning per launch), so
    the CPU test backend gets a plain jit.  The backend choice is read at
    FIRST CALL, not import: jax.default_backend() initializes the
    platform client, and importing this module must stay side-effect-free
    (a second process probing the single-client TPU would otherwise fail
    at import, and jax.config.update calls after import would be pinned
    out)."""
    jitted = None

    def call(*args):
        nonlocal jitted
        if jitted is None:
            jitted = jax.jit(fn) if jax.default_backend() == "cpu" \
                else jax.jit(fn, donate_argnums=0)
        return jitted(*args)

    return call


# Debug/profiling entry point: scripts re-time one device-resident input
# many times, which donation would invalidate after the first call.
# graftlint: disable=nondonated-buffer
verify_compact_jit = jax.jit(verify_compact)


def verify_packed(packed: jnp.ndarray) -> jnp.ndarray:
    """(B, 128) uint8 rows of A || R || S || k -> (B,) bool mask.

    Single-array variant of verify_compact: one host->device transfer per
    batch (each array transfer over a tunneled TPU pays a round trip)."""
    return verify_compact(packed[..., 0:32], packed[..., 32:64],
                          packed[..., 64:96], packed[..., 96:128])


# Re-timeable variant for the profiling scripts (see _jit_donated).
# graftlint: disable=nondonated-buffer
verify_packed_jit = jax.jit(verify_packed)
# Production launch shape for the sidecar engine: its packed buffers are
# freshly transferred per launch and never touched again.
verify_packed_donated = _jit_donated(verify_packed)


def verify_packed_chunked(packed_g: jnp.ndarray) -> jnp.ndarray:
    """(G, B, 128) uint8 -> (G, B) bool: G sub-batches verified by ONE
    program (lax.scan over sub-batches).

    The tunneled TPU pays a fixed 15-20 ms per dispatch+sync regardless of
    batch, while per-conv group counts must stay <= ~1024 for sane compile
    times — so large backlogs go through this shape: group count stays at
    the sub-batch size, but G sub-batches share one dispatch.  This is the
    production launch shape for the sidecar's bulk path and the headline
    bench (scripts/PROFILE.md "Throughput structure").  The mesh twin is
    parallel/sharded_verify.verify_sharded_chunked (graftscale): the
    same scan structure per shard, with the validity counts psum-reduced
    over ICI and the (g, rows) shape set coming from
    parallel/shard_shapes.mesh_chunk_count."""
    def body(_, chunk):
        return None, verify_packed(chunk)
    _, masks = jax.lax.scan(body, None, packed_g)
    return masks


# Re-timeable variant for the profiling scripts (see _jit_donated).
# graftlint: disable=nondonated-buffer
verify_packed_chunked_jit = jax.jit(verify_packed_chunked)
# Production bulk launch shape (the sidecar's backlog drain; bench.py
# builds its own donated outer jit over verify_packed_chunked).
verify_packed_chunked_donated = _jit_donated(verify_packed_chunked)


def verify_prepared(ay: jnp.ndarray, a_sign: jnp.ndarray,
                    ry: jnp.ndarray, r_sign: jnp.ndarray,
                    s_digits: jnp.ndarray,
                    k_digits: jnp.ndarray) -> jnp.ndarray:
    """Device-side Ed25519 verification over a batch.

    Checks [S]B - [k]A == R, split into:
      * [S]B via a fixed-base comb (32 adds against a host-precomputed
        affine table, zero doublings), and
      * [k](-A) via a 4-bit windowed variable-base ladder (64 steps of
        4 doublings + 1 table add against an on-device 16-entry table),
    then one combining add and a projective compare against R. This is
    ~3,350 conv launches vs ~4,900 for the old joint 1-bit ladder — the
    program is conv-throughput-bound (scripts/PROFILE.md).

    Args:
      ay, ry:   (B, 32) int32 canonical y limbs of pubkey / R point.
      a_sign, r_sign: (B,) int32 x-parity bits.
      s_digits: (B, 32) int32 little-endian base-256 digits of S (= bytes).
      k_digits: (B, 64) int32 MSB-first base-16 digits of
                k = SHA512(R||A||M) mod L (host-hashed).
    Returns:
      (B,) bool validity mask (encoding checks done host-side are ANDed by
      the caller).
    """
    batch_shape = ay.shape[:-1]
    if _JOINT_DECOMPRESS:
        # One stacked decompression for A and R: halves the length of the
        # dependent x-recovery pow chain (one conv at 2*batch groups
        # instead of two dependent batch-group convs).
        both_pt, ok_both = decompress_t(
            jnp.concatenate([ay, ry], axis=0),
            jnp.concatenate([a_sign, r_sign], axis=0))
        n = ay.shape[0]
        a_pt = tuple(c[:n] for c in both_pt)
        r_pt = tuple(c[n:] for c in both_pt)
        ok_a, ok_r = ok_both[:n], ok_both[n:]
    else:
        a_pt, ok_a = decompress_t(ay, a_sign)
        r_pt, ok_r = decompress_t(ry, r_sign)

    # -- variable-base half: [k](-A), 4-bit windows ------------------------
    ax, ay_l, az, at = a_pt
    neg_a = (F.neg(ax), ay_l, az, F.neg(at))
    neg_a_cached = to_cached_t(neg_a)
    # 16-entry table of d*(-A), d = 0..15, in cached form.
    entries = [identity_t(batch_shape), neg_a]
    for _ in range(2, 16):
        entries.append(add_t(entries[-1], neg_a_cached))
    cached_entries = [to_cached_t(e) for e in entries]

    if _TUPLE_POINTS:
        # Per-coordinate tables: 4 arrays of (..., 16, 32); selection is 4
        # per-coordinate gathers, and the scan carry is a coordinate tuple
        # (no stacked-layout packing anywhere in the hot loop).
        table_t = tuple(
            jnp.stack([e[c] for e in cached_entries], axis=-2)
            for c in range(4))

        def select_t(digit_row):
            idx = digit_row[..., None, None].astype(jnp.int32)
            return tuple(
                jnp.take_along_axis(tc, idx, axis=-2)[..., 0, :]
                for tc in table_t)

        def ladder_body(p, digit_row):
            p = dbl_t(p, with_t=False)
            p = dbl_t(p, with_t=False)
            p = dbl_t(p, with_t=False)
            p = dbl_t(p)  # the add below reads T
            p = add_t(p, select_t(digit_row))
            return p, None

        ka_pt, _ = jax.lax.scan(ladder_body, identity_t(batch_shape),
                                jnp.moveaxis(k_digits, -1, 0))

        # -- fixed-base half: [S]B via the comb ----------------------------
        comb = jnp.asarray(comb_table())  # (32, 256, 4, 32) constant
        comb_coords = tuple(comb[:, :, c, :] for c in range(4))

        def comb_body(acc, xs):
            digit_row = xs[-1]
            entry = tuple(jnp.take(cj, digit_row, axis=0) for cj in xs[:4])
            return add_t(acc, entry), None

        sb_pt, _ = jax.lax.scan(
            comb_body, identity_t(batch_shape),
            (*comb_coords, jnp.moveaxis(s_digits, -1, 0)))

        lhs = add_t(sb_pt, to_cached_t(ka_pt))  # [S]B - [k]A
        x3, y3, z3 = lhs[0], lhs[1], lhs[2]
        rx, ry_, rz = r_pt[0], r_pt[1], r_pt[2]
    else:
        table = jnp.stack([_pack(*e) for e in cached_entries], axis=-3)

        def ladder_body(p, digit_row):
            p = point_dbl(p, with_t=False)
            p = point_dbl(p, with_t=False)
            p = point_dbl(p, with_t=False)
            p = point_dbl(p)  # the add below reads T
            p = point_add(p, _digit_select(table, digit_row))
            return p, None

        ka_pt, _ = jax.lax.scan(ladder_body, identity_ext(batch_shape),
                                jnp.moveaxis(k_digits, -1, 0))

        comb = jnp.asarray(comb_table())  # (32, 256, 4, 32) constant

        def comb_body(acc, xs):
            comb_j, digit_row = xs
            entry = jnp.take(comb_j, digit_row, axis=0)  # (B, 4, 32)
            return point_add(acc, entry), None

        sb_pt, _ = jax.lax.scan(
            comb_body, identity_ext(batch_shape),
            (comb, jnp.moveaxis(s_digits, -1, 0)))

        lhs = point_add(sb_pt, to_cached(ka_pt))
        x3, y3, z3, _ = _unpack(lhs)
        rx, ry_, rz = r_pt[0], r_pt[1], r_pt[2]

    # -- projective equality: all four cross-products in one conv ----------
    cross = F.canonical(F.mul(_pack(x3, rx, y3, ry_),
                              _pack(rz, z3, rz, z3)))
    ok_eq = jnp.all(cross[..., 0, :] == cross[..., 1, :], axis=-1) & \
            jnp.all(cross[..., 2, :] == cross[..., 3, :], axis=-1)
    return ok_a & ok_r & ok_eq


# Test/debug entry point over already-split arrays; callers (tests,
# eval_device A/B runs) reuse their device-resident inputs across calls.
# graftlint: disable=nondonated-buffer
verify_prepared_jit = jax.jit(verify_prepared)


# ---------------------------------------------------------------------------
# Random-linear-combination batch verification: ONE multi-scalar multiply
# for the whole quorum
# ---------------------------------------------------------------------------
#
# Per-signature verification solves n independent equations
# [S_i]B == R_i + [k_i]A_i — two scalar ladders per vote.  Drawing random
# coefficients z_i and summing z_i * (eq_i) collapses the quorum to ONE
# equation,
#
#     [sum z_i S_i mod L] B  ==  sum [z_i] R_i  +  sum [z_i k_i mod L] A_i,
#
# whose right side is a 2n-point multi-scalar multiplication (MSM).  A
# batch of all-valid votes always satisfies it (the defects sum to exactly
# zero); an invalid vote escapes only if its defect cancels against the
# z-weighted sum, probability ~2^-128 for >=128-bit coefficients (see
# crypto/eddsa.verify_batch_rlc for the PRF and the bisection fallback
# that pinpoints culprits when the combined check fails).
#
# MSM shape (Straus with shared 4-bit windows): per-point 16-entry tables
# (14 batched adds — the same table build the per-signature ladder does),
# then for each of the 64 nibble windows select each point's table entry
# and fold the batch axis with a masked segment-style binary tree of
# point adds (padding/excluded rows select entry 0 = identity, so no
# separate mask tensor is needed).  Windows are processed in chunks of
# _MSM_WINDOW_CHUNK inside one lax.scan — chunking trades conv group
# count (chunk * 2n per level) against scan depth, keeping groups inside
# the ~1024-group compile-time envelope at quorum sizes while the scan
# body still compiles once.  Window sums combine by a 63-step Horner
# ladder (4 doublings + 1 add per window, batch 1), and the fixed-base
# [c]B side reuses the zero-doubling comb.  Total point-op work is
# ~78n + 330 versus ~350n for n per-signature ladders — the arithmetic
# win the RLC check exists for.
#
# Pippenger-style shared buckets (15 buckets per window, scatter by
# digit) were considered and rejected for this substrate: point adds
# cannot ride XLA's scatter/segment-sum (the group law is not an
# elementwise monoid op), so bucket accumulation would need a masked add
# per (bucket, point) pair — 15x the work of the per-point-table Straus
# form on a SIMD machine.  The per-point tables cost 2n*16 points of
# memory (~128 KB at n=512), which is noise next to the conv workspace.

from . import kern as _kern  # noqa: E402  (graftkern Pallas route)
from . import scalar25519 as S  # noqa: E402  (device scalar arithmetic)

_MSM_WINDOW_CHUNK = int(_os.environ.get("HOTSTUFF_TPU_MSM_WINDOW_CHUNK",
                                        "8"))
if 64 % _MSM_WINDOW_CHUNK != 0:
    raise ValueError("HOTSTUFF_TPU_MSM_WINDOW_CHUNK must divide 64")


def msm_window_chunk() -> int:
    """The Straus window-chunk size — env-pinned once at import
    (HOTSTUFF_TPU_MSM_WINDOW_CHUNK, default 8), re-pinnable in-process
    via :func:`set_msm_window_chunk`.  Read at trace time by
    msm_window_sums, so the v5e sweep (bench.py msm_chunk_sweep) can
    measure every value from ONE process instead of re-exec'ing a
    subprocess per value."""
    return _MSM_WINDOW_CHUNK


def set_msm_window_chunk(chunk: int) -> None:
    """Re-pin the window-chunk size in-process.  Clears the global jit
    caches: every compiled MSM program baked the chunk it was traced
    with, so a stale trace would keep the old scan shape.  The chunk
    only trades conv group count against scan depth — results are
    bit-identical across values (asserted in tests/test_kern.py)."""
    global _MSM_WINDOW_CHUNK
    if not isinstance(chunk, int) or chunk < 1 or 64 % chunk != 0:
        raise ValueError(
            f"msm window chunk must be a positive divisor of 64, "
            f"got {chunk!r}")
    if chunk != _MSM_WINDOW_CHUNK:
        _MSM_WINDOW_CHUNK = chunk
        jax.clear_caches()


def msm_table(points: jnp.ndarray) -> jnp.ndarray:
    """(B, 4, 32) ext points -> (B, 16, 4, 32) ext table of 0..15 multiples
    (entry 0 is the identity: digit-0 selections vanish without a mask)."""
    cached_p = to_cached(points)
    entries = [identity_ext(points.shape[:-2]), points]
    for _ in range(2, 16):
        entries.append(point_add(entries[-1], cached_p))
    return jnp.stack(entries, axis=-3)


def _tree_sum(pts: jnp.ndarray) -> jnp.ndarray:
    """(M, ..., 4, 32) ext -> (..., 4, 32): binary tree of point adds over
    the leading axis (M a power of two; identity entries make padding
    free).  log2(M) sequential adds at M/2, M/4, ... conv groups — the
    wide-SIMD segment reduction the MSM rests on."""
    m = pts.shape[0]
    while m > 1:
        m //= 2
        pts = point_add(pts[:m], to_cached(pts[m:]))
    return pts[0]


def msm_window_sums(points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """Per-window sums of a Straus MSM: (64, 4, 32) ext points W_j with
    sum_i [s_i]P_i = sum_j 16^(63-j) W_j (windows MSB-first).

    Args:
      points: (B, 4, 32) ext points.  B is padded to a power of two with
              identity points internally, so any batch size is legal.
      digits: (B, 64) int32 MSB-first 4-bit windows of the scalars
              (unpack_nibbles_msb of canonical 32-byte scalars < L).

    This is the shardable half of the MSM: window sums from disjoint
    point shards simply point-add together (parallel/sharded_verify
    all-gathers them over ICI and tree-combines before the Horner pass).
    """
    b = points.shape[0]
    b_pad = next_pow2(b)
    if b_pad != b:
        points = jnp.concatenate(
            [points, identity_ext((b_pad - b,))], axis=0)
        digits = jnp.pad(digits, [(0, b_pad - b), (0, 0)])
    table = msm_table(points)                        # (B, 16, 4, 32)
    if _kern.use_pallas():
        # graftkern route: selection + tree fused per window, window
        # sums bit-identical to the chunked scan below (the chunk knob
        # does not apply — the kernel grids over single windows).
        return _kern.msm_window_accum(table, digits)
    return _window_sums_lax(table, digits)


def _window_sums_lax(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """The lax reference window accumulator (and the
    HOTSTUFF_TPU_KERN=lax route): per-window table selection + masked
    tree reduction, windows processed in chunks of msm_window_chunk()
    inside one lax.scan.  ``table`` (B, 16, 4, 32) from msm_table,
    ``digits`` (B, 64) with B already a power of two."""
    b_pad = digits.shape[0]
    chunk = msm_window_chunk()
    # (64, B) MSB-first -> (64/chunk, chunk, B)
    dig = jnp.moveaxis(digits, -1, 0).reshape(64 // chunk, chunk, b_pad)

    def chunk_sums(_, dch):
        tab = jnp.broadcast_to(table[None], (chunk, *table.shape))
        sel = _digit_select(tab, dch)                # (chunk, B, 4, 32)
        return None, _tree_sum(jnp.moveaxis(sel, 1, 0))

    _, wsums = jax.lax.scan(chunk_sums, None, dig)   # (64/chunk, chunk,..)
    return wsums.reshape(64, 4, F.NLIMBS)


def msm_horner(wsums: jnp.ndarray) -> jnp.ndarray:
    """(64, 4, 32) MSB-first window sums -> (4, 32) ext total:
    63 x (4 doublings + 1 add) at batch 1."""
    def horner(acc, w):
        acc = point_dbl(acc, with_t=False)
        acc = point_dbl(acc, with_t=False)
        acc = point_dbl(acc, with_t=False)
        acc = point_dbl(acc)
        return point_add(acc, to_cached(w)), None

    acc, _ = jax.lax.scan(horner, identity_ext(()), wsums)
    return acc


def msm_straus(points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """sum_i [s_i] P_i: (B, 4, 32) ext points + (B, 64) MSB-first nibble
    digits -> (4, 32) ext sum.  See msm_window_sums for the shape rules."""
    return msm_horner(msm_window_sums(points, digits))


def comb_mul_base(c_digits: jnp.ndarray) -> jnp.ndarray:
    """[c]B for one scalar given as (32,) int32 base-256 little-endian
    digits: the fixed-base comb at batch shape () — 32 adds, zero
    doublings."""
    comb = jnp.asarray(comb_table())                 # (32, 256, 4, 32)

    def body(acc, xs):
        comb_j, digit = xs
        return point_add(acc, jnp.take(comb_j, digit, axis=0)), None

    acc, _ = jax.lax.scan(body, identity_ext(()),
                          (comb, c_digits.astype(jnp.int32)))
    return acc


def rlc_partials(packed: jnp.ndarray, z: jnp.ndarray):
    """Shard-local half of the RLC check.

    Args:
      packed: (B, 128) uint8 rows of A || R || S || k.
      z:      (B, 32) uint8 canonical coefficient rows; an ALL-ZERO row is
              excluded (zero scalars select only identity table entries
              and its decompression result is ignored) — bucket padding
              and host-rejected votes are plain zero rows.
    Returns:
      wsums:   (64, 4, 32) MSB-first MSM window sums of
               sum [z_i k_i]A_i + [z_i]R_i over this shard's rows.
      u_sum:   (32,) int32 limb-wise sum of the z_i*S_i mod L terms
               (fold with scalar25519.reduce_limbsum_mod_l — it commutes
               with an ICI psum).
      bad:     () int32 count of included rows whose A or R failed
               decompression.
    Window sums from disjoint shards point-add together, which is what
    lets the MSM buckets shard across the mesh
    (parallel/sharded_verify.verify_rlc_sharded).
    """
    ay, a_sign = split_y_sign(packed[..., 0:32])
    ry, r_sign = split_y_sign(packed[..., 32:64])
    s_l = packed[..., 64:96].astype(jnp.int32)
    k_l = packed[..., 96:128].astype(jnp.int32)
    z_l = z.astype(jnp.int32)

    present = jnp.any(z_l != 0, axis=-1)
    # A points first, R points second — matching the digit concat below.
    pts, ok = decompress(jnp.concatenate([ay, ry], axis=0),
                         jnp.concatenate([a_sign, r_sign], axis=0))
    present2 = jnp.concatenate([present, present], axis=0)
    bad = jnp.sum(~ok & present2).astype(jnp.int32)

    w = S.mul_mod_l(z_l, k_l)          # z_i * k_i mod L  (A_i scalars)
    u = S.mul_mod_l(z_l, s_l)          # z_i * S_i mod L

    # Torsion-exact CRT lift to the full-group exponent 8L.  E(Fp) is
    # Z/8 x Z/L: a scalar acts mod L on the prime-order component but
    # mod 8 on a point's 8-torsion component, and reducing z*k mod L
    # scrambles the mod-8 residue — a combined check built from the
    # reduced scalars weighs each row's torsion defect by an
    # L-reduction artifact an adversary can grind (a mixed-order pubkey
    # A' + T would slip through whenever the artifact hits 0 mod 8).
    # Lifting A's scalar to w' ≡ w (mod L), w' ≡ k (mod 8) and R's to
    # z' ≡ z (mod L), z' ≡ 1 (mod 8) makes every row's torsion defect
    # enter the sum with the SAME coefficient the per-signature
    # cofactorless equation uses — so a single defective row passes or
    # fails the combined check exactly as verify_compact would.
    # (L ≡ 5 (mod 8), self-inverse; excluded rows keep scalar 0.)
    present_i = present.astype(jnp.int32)
    t_w = (5 * ((k_l[..., 0] & 7) - (w[..., 0] & 7))) % 8 * present_i
    t_z = (5 * (1 - (z_l[..., 0] & 7))) % 8 * present_i
    w_lift = S.add_small_multiple_of_l(w, t_w)
    z_lift = S.add_small_multiple_of_l(z_l, t_z)

    digits = unpack_nibbles_msb(jnp.concatenate([w_lift, z_lift], axis=0))
    wsums = msm_window_sums(pts, digits)
    return wsums, jnp.sum(u, axis=-2), bad


def rlc_finish(wsums: jnp.ndarray, u_limbsum: jnp.ndarray,
               bad: jnp.ndarray) -> jnp.ndarray:
    """Combine (possibly mesh-reduced) RLC partials into the () bool
    verdict: Horner-fold the window sums, comb [c]B from the reduced
    scalar sum, compare projectively, and veto on any bad point."""
    c = S.reduce_limbsum_mod_l(u_limbsum)
    msm = msm_horner(wsums)            # sum [w_i]A_i + [z_i]R_i
    cb = comb_mul_base(c)              # [c]B

    x1, y1, z1, _ = _unpack(cb)
    x2, y2, z2, _ = _unpack(msm)
    cross = F.canonical(F.mul(_pack(x1, x2, y1, y2),
                              _pack(z2, z1, z2, z1)))
    eq = jnp.all(cross[..., 0, :] == cross[..., 1, :], axis=-1) & \
        jnp.all(cross[..., 2, :] == cross[..., 3, :], axis=-1)
    return (bad == 0) & eq


def verify_rlc_packed(packed: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
    """(B, 128) uint8 rows of A || R || S || k  +  (B, 32) uint8 canonical
    coefficient rows -> () bool: the whole batch passes the combined
    random-linear-combination check.  An all-excluded batch returns True
    (vacuous).  B should be a power-of-two bucket (crypto/eddsa._bucket
    discipline, the shapes warmup compiles); scalar products z*S and z*k
    reduce mod L on device (ops/scalar25519), so the caller only ships
    160 bytes per row.
    """
    return rlc_finish(*rlc_partials(packed, z))


# Re-timeable variant for profiling scripts (see _jit_donated).
# graftlint: disable=nondonated-buffer
verify_rlc_packed_jit = jax.jit(verify_rlc_packed)
# Production launch shape: each packed buffer is transferred once and
# consumed once (the z rows are small and not donated).
verify_rlc_packed_donated = _jit_donated(verify_rlc_packed)

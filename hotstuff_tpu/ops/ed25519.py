"""Ed25519 curve operations and batched signature verification on TPU.

The device-side half of the framework's equivalent of the reference's
``Signature::verify`` / ``Signature::verify_batch``
(reference: crypto/src/lib.rs:177-224).  Scalars, hashing (SHA-512) and
encoding checks live on the host (see hotstuff_tpu/crypto/eddsa.py); the
device receives pre-parsed limb arrays + the 2-bit digit schedule of the
double-scalar multiplication and returns a per-signature validity mask —
the mask shape is what quorum-certificate verification consumes
(consensus/src/messages.rs:180-198 in the reference).

TPU-first design notes:
* Points are dense ``(..., 4, 32)`` int32 arrays (X, Y, Z, T) in extended
  twisted-Edwards coordinates — a pytree-free layout that vmaps/shards
  cleanly along the batch axis.
* All control flow is static: complete addition formulas (no exceptional
  cases), `lax.scan` over a fixed 256-entry digit schedule, constant-time
  table selection via `take_along_axis` (gather on device).
* The per-signature lookup table {O, B, -A, B-A} is built on device; B is a
  compile-time constant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field25519 as F
from ..utils.intmath import BX, BY, D, L, P, SQRT_M1

K2D = (2 * D) % P

_const = F.constant


# ---------------------------------------------------------------------------
# Point representation helpers.  ext = (X, Y, Z, T); cached = (Y+X, Y-X, Z, 2dT)
# ---------------------------------------------------------------------------

_EXT_X, _EXT_Y, _EXT_Z, _EXT_T = range(4)


def _pack(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=-2)


def _unpack(p):
    return p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]


def identity_ext(batch_shape=()) -> jnp.ndarray:
    one, zero = _const(1), _const(0)
    pt = _pack(zero, one, one, zero)
    return jnp.broadcast_to(pt, (*batch_shape, 4, F.NLIMBS))


def basepoint_ext() -> jnp.ndarray:
    return _pack(_const(BX), _const(BY), _const(1), _const(BX * BY % P))


def to_cached(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z, t = _unpack(p)
    k2d = jnp.broadcast_to(_const(K2D), t.shape)
    return _pack(F.add(y, x), F.sub(y, x), z, F.mul(t, k2d))


def cached_neg(c: jnp.ndarray) -> jnp.ndarray:
    """cached(P) -> cached(-P): swap (Y+X, Y-X), negate 2dT."""
    ypx, ymx, z, t2d = _unpack(c)
    return _pack(ymx, ypx, z, F.neg(t2d))


def point_add(p: jnp.ndarray, qc: jnp.ndarray) -> jnp.ndarray:
    """Complete unified addition, ext + cached -> ext (7 field muls).

    add-2008-hwcd-3 for a=-1 (the ref10 ge_add shape) — complete on the
    twisted Edwards curve, so it needs no doubling/identity branches: ideal
    for SIMD/scan execution on TPU.
    """
    x1, y1, z1, t1 = _unpack(p)
    ypx2, ymx2, z2, t2d2 = _unpack(qc)
    a = F.mul(F.sub(y1, x1), ymx2)
    b = F.mul(F.add(y1, x1), ypx2)
    c = F.mul(t1, t2d2)
    zz = F.mul(z1, z2)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return _pack(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_dbl(p: jnp.ndarray) -> jnp.ndarray:
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4M + 4S."""
    x1, y1, z1, _ = _unpack(p)
    a = F.sqr(x1)
    b = F.sqr(y1)
    zz = F.sqr(z1)
    c = F.add(zz, zz)
    e = F.sub(F.sub(F.sqr(F.add(x1, y1)), a), b)   # 2*X1*Y1
    g = F.sub(b, a)                                 # B - A   (= D + B, D = -A)
    f = F.sub(g, c)
    h = F.neg(F.add(a, b))                          # -(A+B)  (= D - B)
    return _pack(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


# ---------------------------------------------------------------------------
# Decompression (x-recovery), fully on device
# ---------------------------------------------------------------------------

def decompress(y_limbs: jnp.ndarray, sign_bit: jnp.ndarray):
    """(..., 32) canonical y limbs + (...,) sign bit -> (ext point, ok mask).

    RFC 8032 §5.1.3 x-recovery: x = u v^3 (u v^7)^((p-5)/8), with u = y²-1,
    v = d y²+1; multiply by sqrt(-1) when v x² = -u; fail when neither.
    The (p-5)/8 power runs as a scan over a constant bit schedule.
    """
    one = jnp.broadcast_to(_const(1), y_limbs.shape)
    dd = jnp.broadcast_to(_const(D), y_limbs.shape)
    y2 = F.sqr(y_limbs)
    u = F.sub(y2, one)
    v = F.add(F.mul(dd, y2), one)
    v3 = F.mul(F.sqr(v), v)
    v7 = F.mul(F.sqr(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    vxx = F.mul(v, F.sqr(x))
    ok_direct = F.eq(vxx, u)
    ok_twist = F.eq(vxx, F.neg(u))
    x = jnp.where(ok_twist[..., None],
                  F.mul(x, jnp.broadcast_to(_const(SQRT_M1), x.shape)), x)
    ok = ok_direct | ok_twist
    # sign adjustment; x == 0 with sign 1 is invalid
    x_zero = F.is_zero(x)
    flip = (F.parity(x) != sign_bit) & ~x_zero
    x = jnp.where(flip[..., None], F.neg(x), x)
    ok = ok & ~(x_zero & (sign_bit == 1))
    t = F.mul(x, y_limbs)
    z = jnp.broadcast_to(_const(1), y_limbs.shape)
    return _pack(x, y_limbs, z, t), ok


# ---------------------------------------------------------------------------
# Batched verification
# ---------------------------------------------------------------------------

def _digit_select(table: jnp.ndarray, digit: jnp.ndarray) -> jnp.ndarray:
    """table (..., 4tab, 4coord, 32), digit (...,) in [0,4) -> (..., 4, 32)."""
    idx = digit[..., None, None, None].astype(jnp.int32)
    return jnp.take_along_axis(table, idx, axis=-3)[..., 0, :, :]


def unpack_digits(s_bytes: jnp.ndarray, k_bytes: jnp.ndarray) -> jnp.ndarray:
    """(B, 32) uint8 little-endian S and k scalars -> (B, 256) int32
    MSB-first 2-bit joint digits bit_i(S) + 2*bit_i(k).

    Runs on device: the host ships 64 bytes per signature instead of a
    1 KB digit schedule — on a tunneled TPU the host->device transfer is
    the bottleneck, not the ladder itself.
    """
    shifts = jnp.arange(8, dtype=jnp.int32)
    def bits_le(b):
        # (B, 32) -> (B, 256) little-endian bit order
        x = (b.astype(jnp.int32)[..., None] >> shifts) & 1
        return x.reshape(*b.shape[:-1], 256)
    s_bits = bits_le(s_bytes)
    k_bits = bits_le(k_bytes)
    return (s_bits + 2 * k_bits)[..., ::-1]  # MSB-first schedule


def split_y_sign(y_bytes: jnp.ndarray):
    """(B, 32) uint8 compressed point -> ((B, 32) int32 y limbs with bit
    255 cleared, (B,) int32 x-sign bit). Device-side byte parsing."""
    y = y_bytes.astype(jnp.int32)
    sign = y[..., 31] >> 7
    y = y.at[..., 31].set(y[..., 31] & 0x7F)
    return y, sign


def verify_compact(a_bytes: jnp.ndarray, r_bytes: jnp.ndarray,
                   s_bytes: jnp.ndarray, k_bytes: jnp.ndarray) -> jnp.ndarray:
    """Device-side Ed25519 verification from raw wire bytes.

    Args (all (B, 32) uint8): compressed pubkey A, compressed R, scalar S
    (little-endian), and the host-hashed challenge k = SHA512(R||A||M) mod L.
    130 bytes/signature cross the host->device boundary; limb conversion,
    sign extraction and the 512-entry bit unpack all happen on device.

    Returns (B,) bool validity mask (host-side canonicality checks are
    ANDed by the caller, crypto/eddsa.verify_batch).
    """
    ay, a_sign = split_y_sign(a_bytes)
    ry, r_sign = split_y_sign(r_bytes)
    digits = unpack_digits(s_bytes, k_bytes)
    return verify_prepared(ay, a_sign, ry, r_sign, digits)


verify_compact_jit = jax.jit(verify_compact)


def verify_packed(packed: jnp.ndarray) -> jnp.ndarray:
    """(B, 128) uint8 rows of A || R || S || k -> (B,) bool mask.

    Single-array variant of verify_compact: one host->device transfer per
    batch (each array transfer over a tunneled TPU pays a round trip)."""
    return verify_compact(packed[..., 0:32], packed[..., 32:64],
                          packed[..., 64:96], packed[..., 96:128])


verify_packed_jit = jax.jit(verify_packed)


def verify_prepared(ay: jnp.ndarray, a_sign: jnp.ndarray,
                    ry: jnp.ndarray, r_sign: jnp.ndarray,
                    digits: jnp.ndarray) -> jnp.ndarray:
    """Device-side Ed25519 verification over a batch.

    Checks [S]B - [k]A == R via one joint double-scalar ladder.

    Args:
      ay, ry:   (B, 32) int32 canonical y limbs of pubkey / R point.
      a_sign, r_sign: (B,) int32 x-parity bits.
      digits:   (B, 256) int32 in [0,4): MSB-first 2-bit schedule
                bit_i(S) + 2*bit_i(k), k = SHA512(R||A||M) mod L (host-hashed).
    Returns:
      (B,) bool validity mask (encoding checks done host-side are ANDed by
      the caller).
    """
    batch_shape = ay.shape[:-1]
    a_pt, ok_a = decompress(ay, a_sign)
    r_pt, ok_r = decompress(ry, r_sign)

    neg_a = cached_neg(to_cached(a_pt))
    b_ext = jnp.broadcast_to(basepoint_ext(), (*batch_shape, 4, F.NLIMBS))
    b_cached = to_cached(b_ext)
    b_minus_a = to_cached(point_add(b_ext, neg_a))
    id_cached = to_cached(identity_ext(batch_shape))
    # table index = bit(S) + 2*bit(k): [O, B, -A, B-A]
    table = jnp.stack([id_cached, b_cached, neg_a, b_minus_a], axis=-3)

    def body(p, digit_row):
        p = point_dbl(p)
        p = point_add(p, _digit_select(table, digit_row))
        return p, None

    p0 = identity_ext(batch_shape)
    # scan over the 256 digit positions (leading axis), batch stays vectorized
    digits_t = jnp.moveaxis(digits, -1, 0)
    p_final, _ = jax.lax.scan(body, p0, digits_t)

    x3, y3, z3, _ = _unpack(p_final)
    rx, ry_, rz, _ = _unpack(r_pt)
    ok_eq = F.eq(F.mul(x3, rz), F.mul(rx, z3)) & \
            F.eq(F.mul(y3, rz), F.mul(ry_, z3))
    return ok_a & ok_r & ok_eq


verify_prepared_jit = jax.jit(verify_prepared)

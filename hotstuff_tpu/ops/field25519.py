"""GF(2^255 - 19) arithmetic, batched, in JAX — the TPU compute substrate for
Ed25519 verification.

Design (TPU-first, not a port):

* A field element is 32 radix-2^8 limbs stored as ``int32``, shape ``(..., 32)``,
  little-endian.  8-bit limbs give huge accumulation headroom in int32 and make
  every op a static-shape vector op on the VPU.
* Polynomial (schoolbook) multiplication is expressed as one outer product plus
  a constant 0/1 matmul ``(..., 1024) @ (1024, 63)`` — partial-product sums are
  < 2^23 so they are exact in float32, which puts the inner loop of the whole
  signature-verification workload on the MXU.
* Carry propagation is a *parallel* carry: every limb simultaneously keeps its
  low byte and passes its high bits one limb up (the carry out of limb 31 wraps
  to limb 0 multiplied by 38, since 2^256 ≡ 38 (mod p)).  A fixed, statically
  bounded number of such steps restores the "weak" invariant limbs < 2^9.
  No data-dependent control flow anywhere — everything jits and vmaps.

Weak-normal form invariant: limbs in [0, 2^9); the represented value is only
meaningful mod p.  Canonical form (limbs < 2^8 and value < p) is produced once
at the end of a computation by :func:`canonical`.

Reference parity: this module underpins the TPU equivalent of
``Signature::verify_batch`` (reference: crypto/src/lib.rs:210-223), the hot
primitive of quorum-certificate verification (consensus/src/messages.rs:197).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import kern as _kern

NLIMBS = 32
LIMB_BITS = 8
LIMB_MASK = (1 << LIMB_BITS) - 1
P = 2**255 - 19

# Canonical base-256 digits of p (little-endian): [237, 255*30, 127].
_P_DIGITS = [(P >> (8 * i)) & 0xFF for i in range(NLIMBS)]

# Subtraction bias: 8*p spread over limbs so every limb dominates any weak
# limb (< 2^9).  8p = 2^258 - 152 -> limbs [8*237, 8*255 x30, 8*127]
# = [1896, 2040 x30, 1016]; all >= 511.
_SUB_BIAS = [8 * d for d in _P_DIGITS]

# Matmul precision for the limb-product convolution. HIGH (bf16x3 passes)
# measured exact for this workload's 23-bit sums on real TPU (see
# mul_selfcheck, which bench.py runs before timing) and ~16% faster than
# HIGHEST; override with HOTSTUFF_TPU_MUL_PRECISION=highest if a backend
# ever fails the self-check.
import os as _os

_PRECISION = {
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}[_os.environ.get("HOTSTUFF_TPU_MUL_PRECISION", "high").lower()]


# ---------------------------------------------------------------------------
# Host <-> limb conversion helpers (numpy / python ints; not jitted)
# ---------------------------------------------------------------------------

def to_limbs(x: int) -> np.ndarray:
    """Python int (mod p not required) -> (32,) int32 canonical byte limbs."""
    x = int(x) % (1 << 256)
    return np.array([(x >> (8 * i)) & 0xFF for i in range(NLIMBS)], dtype=np.int32)


def from_limbs(limbs) -> int:
    """(32,) limbs (any magnitude) -> python int value."""
    limbs = np.asarray(limbs).reshape(NLIMBS)
    return sum(int(v) << (8 * i) for i, v in enumerate(limbs))


def batch_to_limbs(xs) -> np.ndarray:
    """Iterable of python ints -> (N, 32) int32 limbs."""
    return np.stack([to_limbs(x) for x in xs])


def batch_from_limbs(limbs) -> list[int]:
    limbs = np.asarray(limbs, dtype=np.int64)
    out = []
    for row in limbs.reshape(-1, NLIMBS):
        out.append(sum(int(v) << (8 * i) for i, v in enumerate(row)))
    return out


def constant(x: int) -> jnp.ndarray:
    """Module-load-time constant as (32,) int32 limbs."""
    return jnp.asarray(to_limbs(x % P))


# ---------------------------------------------------------------------------
# Carry propagation
# ---------------------------------------------------------------------------

def _carry_step(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry step.

    Every limb keeps its low 8 bits; its high bits move one limb up.  The
    carry out of limb 31 wraps around to limb 0 scaled by 38 (2^256 ≡ 38 mod p).
    Value is preserved mod p.  Carry magnitudes shrink ~8 bits per step.
    """
    lo = x & LIMB_MASK
    hi = x >> LIMB_BITS
    wrapped = jnp.roll(hi, 1, axis=-1)
    scale = jnp.ones((NLIMBS,), dtype=jnp.int32).at[0].set(38)
    return lo + wrapped * scale


def weak_normalize(x: jnp.ndarray, steps: int) -> jnp.ndarray:
    for _ in range(steps):
        x = _carry_step(x)
    return x


# ---------------------------------------------------------------------------
# Field ops (weak-normal in, weak-normal out; shapes (..., 32) int32)
# ---------------------------------------------------------------------------

def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b.  Inputs limbs < 2^9 -> sum < 2^10 -> one carry step -> < 2^9.

    (carry <= 3; limb0 <= 255 + 38*3 = 369 < 512.)
    """
    return _carry_step(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod p) without negative intermediates: adds the 8p bias whose
    every limb (>= 1016) dominates any weak limb of b.  Result limbs < 2^12
    -> two carry steps restore < 2^9."""
    bias = jnp.asarray(_SUB_BIAS, dtype=jnp.int32)
    x = a + bias - b
    return _carry_step(_carry_step(x))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod p (weak).

    Routed: ``HOTSTUFF_TPU_KERN=pallas`` dispatches the graftkern fused
    kernel (ops/kern/field_mul — conv + wrap-38 fold + carries in one
    VMEM-resident pass), bit-identical to the lax reference below; the
    route is read at trace time (ops/kern.set_mode clears the caches).
    """
    if _kern.use_pallas():
        return _kern.field_mul(a, b)
    return _mul_lax(a, b)


def _mul_lax(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """The lax reference multiply (and the HOTSTUFF_TPU_KERN=lax route).

    The schoolbook product is a depthwise (per-signature-kernel) 1-D
    convolution: out[b] = a[b] conv b[b], exactly
    ``lax.conv_general_dilated`` with ``feature_group_count = batch`` and a
    lane-flipped kernel. That costs 32x63 MACs per element — 64x less
    arithmetic than flattening the outer product through a one-hot matmul,
    which ran at fp32-MXU peak multiplying mostly zeros. Partial-product
    sums < 32 * (2^9)^2 = 2^23: exact in float32. The 38-fold keeps
    coefficients < 39 * 2^23 < 2^28.6 (int32-safe); four parallel carry
    steps restore limbs < 2^9."""
    batch_shape = a.shape[:-1]
    n = 1
    for d in batch_shape:
        n *= d
    lhs = a.reshape(1, n, NLIMBS).astype(jnp.float32)
    rhs = jnp.flip(b.reshape(n, 1, NLIMBS), -1).astype(jnp.float32)
    coeffs = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(NLIMBS - 1, NLIMBS - 1)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=n,
        precision=_PRECISION,
    ).reshape(*batch_shape, 2 * NLIMBS - 1).astype(jnp.int32)
    lo, hi = coeffs[..., :NLIMBS], coeffs[..., NLIMBS:]
    folded = lo + 38 * jnp.pad(hi, [(0, 0)] * (hi.ndim - 1) + [(0, 1)])
    return weak_normalize(folded, 4)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_selfcheck(batch: int = 256, seed: int = 0) -> None:
    """Assert the convolution path is bit-exact on the CURRENT backend for
    adversarial full-range weak limbs. Cheap (one jit call); bench.py and
    deployments should run it once at startup — if a future TPU generation
    lowers Precision.HIGH in a non-exact way this trips immediately instead
    of corrupting verification masks silently."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 512, (batch, NLIMBS))
    b = rng.integers(0, 512, (batch, NLIMBS))
    a[0, :] = 511
    b[0, :] = 511
    got = batch_from_limbs(np.asarray(
        canonical(mul(jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32)))))
    want = [(x * y) % P for x, y in zip(batch_from_limbs(a),
                                        batch_from_limbs(b))]
    if got != want:
        raise AssertionError(
            "field multiply is not exact on this backend; set "
            "HOTSTUFF_TPU_MUL_PRECISION=highest")


# ---------------------------------------------------------------------------
# Canonicalization and comparison
# ---------------------------------------------------------------------------

def _sequential_carry(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact ripple carry over the 32 limbs (unrolled; used only at the ends
    of a computation).  Returns (limbs in [0,256), carry_out)."""
    limbs = []
    carry = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        t = x[..., i] + carry
        limbs.append(t & LIMB_MASK)
        carry = t >> LIMB_BITS
    return jnp.stack(limbs, axis=-1), carry


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """If x >= p (x < 2^256, limbs canonical bytes), subtract p."""
    p_digits = jnp.asarray(_P_DIGITS, dtype=jnp.int32)
    limbs = []
    borrow = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        d = x[..., i] - p_digits[i] - borrow
        borrow = (d < 0).astype(jnp.int32)
        limbs.append(d + (borrow << LIMB_BITS))
    sub_res = jnp.stack(limbs, axis=-1)
    keep = (borrow > 0)[..., None]  # borrow out => x < p => keep x
    return jnp.where(keep, x, sub_res)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Weak element -> canonical limbs (bytes, value in [0, p))."""
    # Value < 2^9 * (2^256-1)/255 < 2^257.01 -> first carry_out <= 2.
    x, carry = _sequential_carry(x)
    x = x.at[..., 0].add(38 * carry)
    # Now value < 2^256 + 77; second pass carry_out <= 1 with residue <= 76.
    x, carry = _sequential_carry(x)
    x = x.at[..., 0].add(38 * carry)  # limb0 <= 76 + 38 < 256: no more carries
    x = _cond_sub_p(x)
    return _cond_sub_p(x)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality of weak elements -> bool shape (...,)."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical value (the Ed25519 'sign' of x)."""
    return canonical(a)[..., 0] & 1


# ---------------------------------------------------------------------------
# Exponentiation by fixed public exponents (scan over constant bit schedule)
# ---------------------------------------------------------------------------

def pow_const(x: jnp.ndarray, exponent: int, window: int = 4) -> jnp.ndarray:
    """x ** exponent mod p for a static python-int exponent.

    Left-to-right windowed square-and-multiply over a *constant* digit
    schedule via lax.scan: each step is `window` squarings plus one multiply
    by a table entry (x^0..x^(2^w - 1), built once). Program time on TPU is
    bounded by conv-launch count, so for the all-ones-ish Ed25519 exponents
    (p-2, (p-5)/8) w=4 cuts launches from ~2/bit to ~1.25/bit.
    """
    assert exponent >= 0
    nbits = max(1, exponent.bit_length())
    nsteps = -(-nbits // window)
    digits = [(exponent >> (window * (nsteps - 1 - i))) & ((1 << window) - 1)
              for i in range(nsteps)]
    digits_arr = jnp.asarray(digits, dtype=jnp.int32)

    # Table x^0..x^(2^w-1): 2^w - 2 sequential muls, built once.
    one = jnp.broadcast_to(constant(1), x.shape).astype(jnp.int32)
    entries = [one, x]
    for _ in range(2, 1 << window):
        entries.append(mul(entries[-1], x))
    table = jnp.stack(entries)  # (2^w, *x.shape)

    def body(acc, digit):
        for _ in range(window):
            acc = sqr(acc)
        acc = mul(acc, jnp.take(table, digit, axis=0))
        return acc, None

    acc, _ = jax.lax.scan(body, one, digits_arr)
    return acc


def inv(x: jnp.ndarray) -> jnp.ndarray:
    """x^(p-2) — Fermat inverse (x=0 -> 0)."""
    return pow_const(x, P - 2)


def _pow_2k(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x^(2^k) — k squarings; scanned for k >= 8 to keep the program small."""
    if k < 8:
        for _ in range(k):
            x = sqr(x)
        return x
    out, _ = jax.lax.scan(lambda a, _: (sqr(a), None), x, None, length=k)
    return out


def pow_p58(x: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3), the x-recovery exponent.

    Uses the standard ref10-style addition chain (2^252 - 3 =
    4*(2^250 - 1) + 1): 251 squarings + 11 multiplies ≈ 262 dependent ops,
    vs ~329 for the generic 4-bit windowed pow_const — the decompression
    pow chain is the longest serial dependency in verification, so ~20%
    off it is free latency.
    """
    x2 = mul(sqr(x), x)                       # x^(2^2 - 1)
    x4 = mul(_pow_2k(x2, 2), x2)              # x^(2^4 - 1)
    x5 = mul(sqr(x4), x)                      # x^(2^5 - 1)
    x10 = mul(_pow_2k(x5, 5), x5)             # x^(2^10 - 1)
    x20 = mul(_pow_2k(x10, 10), x10)          # x^(2^20 - 1)
    x40 = mul(_pow_2k(x20, 20), x20)          # x^(2^40 - 1)
    x50 = mul(_pow_2k(x40, 10), x10)          # x^(2^50 - 1)
    x100 = mul(_pow_2k(x50, 50), x50)         # x^(2^100 - 1)
    x200 = mul(_pow_2k(x100, 100), x100)      # x^(2^200 - 1)
    x250 = mul(_pow_2k(x200, 50), x50)        # x^(2^250 - 1)
    return mul(_pow_2k(x250, 2), x)           # x^(2^252 - 3)

"""Cloud instance lifecycle (create/start/stop/terminate/hosts), gated on
boto3 availability — the reference's InstanceManager
(benchmark/benchmark/instance.py:18-263 capability). In environments
without cloud credentials/SDKs the harness still fully works against an
explicit host list (see remote.Bench).
"""

from __future__ import annotations

from collections import defaultdict

from .settings import Settings
from .utils import BenchError, Print


class InstanceManager:
    INSTANCE_NAME = "hotstuff-tpu-node"
    SECURITY_GROUP_NAME = "hotstuff-tpu"

    def __init__(self, settings):
        assert isinstance(settings, Settings)
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise BenchError(
                "Cloud instance management needs boto3 (not installed); "
                "pass an explicit host list to remote.Bench instead", e)
        import boto3

        self.settings = settings
        self.clients = {
            region: boto3.client("ec2", region_name=region)
            for region in settings.aws_regions
        }

    @classmethod
    def make(cls, settings_file="settings.json"):
        return cls(Settings.load(settings_file))

    def _filters(self):
        return [{"Name": "tag:Name", "Values": [self.INSTANCE_NAME]}]

    def _instances(self, state):
        ids, ips = defaultdict(list), defaultdict(list)
        for region, client in self.clients.items():
            r = client.describe_instances(Filters=self._filters())
            for reservation in r["Reservations"]:
                for inst in reservation["Instances"]:
                    if inst["State"]["Name"] in state:
                        ids[region].append(inst["InstanceId"])
                        if "PublicIpAddress" in inst:
                            ips[region].append(inst["PublicIpAddress"])
        return ids, ips

    def create_instances(self, instances_per_region):
        for region, client in self.clients.items():
            # Open the three benchmark ports + ssh.
            try:
                sg = client.create_security_group(
                    GroupName=self.SECURITY_GROUP_NAME,
                    Description="hotstuff-tpu benchmark")
                base = self.settings.base_port
                perms = [
                    {"IpProtocol": "tcp",
                     "FromPort": p, "ToPort": p,
                     "IpRanges": [{"CidrIp": "0.0.0.0/0"}]}
                    for p in (22, base, base - 1000, base - 2000)
                ]
                client.authorize_security_group_ingress(
                    GroupId=sg["GroupId"], IpPermissions=perms)
            except client.exceptions.ClientError:
                pass  # group exists
            client.run_instances(
                ImageId=self._ubuntu_ami(client),
                InstanceType=self.settings.instance_type,
                KeyName=self.settings.key_name,
                MinCount=instances_per_region,
                MaxCount=instances_per_region,
                SecurityGroups=[self.SECURITY_GROUP_NAME],
                TagSpecifications=[{
                    "ResourceType": "instance",
                    "Tags": [{"Key": "Name",
                              "Value": self.INSTANCE_NAME}],
                }])
        Print.info(f"Created {instances_per_region} instances per region")

    @staticmethod
    def _ubuntu_ami(client):
        images = client.describe_images(
            Owners=["099720109477"],  # Canonical
            Filters=[{
                "Name": "name",
                "Values": ["ubuntu/images/hvm-ssd/ubuntu-jammy-22.04-"
                           "amd64-server-*"],
            }])["Images"]
        return sorted(images, key=lambda x: x["CreationDate"])[-1]["ImageId"]

    def start_instances(self):
        ids, _ = self._instances(["stopped", "stopping"])
        for region, client in self.clients.items():
            if ids[region]:
                client.start_instances(InstanceIds=ids[region])
        Print.info("Starting instances...")

    def stop_instances(self):
        ids, _ = self._instances(["pending", "running"])
        for region, client in self.clients.items():
            if ids[region]:
                client.stop_instances(InstanceIds=ids[region])
        Print.info("Stopping instances...")

    def terminate_instances(self):
        ids, _ = self._instances(
            ["pending", "running", "stopping", "stopped"])
        for region, client in self.clients.items():
            if ids[region]:
                client.terminate_instances(InstanceIds=ids[region])
        Print.info("Terminating instances...")

    def hosts(self, flat=True):
        _, ips = self._instances(["pending", "running"])
        return [x for y in ips.values() for x in y] if flat else dict(ips)

    def print_info(self):
        hosts = self.hosts(flat=False)
        text = ""
        for region, ips in hosts.items():
            text += f"\n Region: {region.upper()}\n"
            for i, ip in enumerate(ips):
                text += f"{i:>6}: ssh -i {self.settings.key_path} "
                text += f"ubuntu@{ip}\n"
        Print.info(text or " No instances")

"""Benchmark configuration: committee/parameters JSON writers matching the
C++ node's readers (native/src/node/config.cpp), plus bench/plot parameter
validation. Mirrors benchmark/benchmark/config.py:8-173 in the reference —
the committee schema is wire-compatible with the node so harness and node
evolve together.
"""

from __future__ import annotations

import json
from collections import OrderedDict


class ConfigError(Exception):
    pass


class Key:
    def __init__(self, name, secret):
        self.name = name
        self.secret = secret

    @classmethod
    def from_file(cls, filename):
        assert isinstance(filename, str)
        with open(filename, "r") as f:
            data = json.load(f)
        return cls(data["name"], data["secret"])


class Committee:
    """Address book for consensus + mempool, one authority per node.

    consensus: one address (peer consensus messages)
    mempool: transactions_address (:front, clients) + mempool_address (peers)
    """

    def __init__(self, names, consensus_addr, front_addr, mempool_addr,
                 bls_pubkeys=None):
        inputs = [names, consensus_addr, front_addr, mempool_addr]
        assert all(isinstance(x, list) for x in inputs)
        assert all(isinstance(x, str) for y in inputs for x in y)
        assert len({len(x) for x in inputs}) == 1
        assert bls_pubkeys is None or len(bls_pubkeys) == len(names)

        self.names = names
        self.consensus = consensus_addr
        self.front = front_addr
        self.mempool = mempool_addr
        self.bls_pubkeys = bls_pubkeys  # base64 96-byte G1, scheme=bls only

        self.json = {
            "consensus": self._build_consensus(),
            "mempool": self._build_mempool(),
        }

    def _build_consensus(self):
        node = {}
        for i, (name, address) in enumerate(zip(self.names, self.consensus)):
            entry = {"stake": 1, "address": address}
            if self.bls_pubkeys:
                entry["bls_pubkey"] = self.bls_pubkeys[i]
            node[name] = entry
        return {"authorities": node, "epoch": 1}

    def _build_mempool(self):
        node = {}
        for name, front, mempool in zip(self.names, self.front, self.mempool):
            node[name] = {
                "stake": 1,
                "transactions_address": front,
                "mempool_address": mempool,
            }
        return {"authorities": node, "epoch": 1}

    def print(self, filename):
        assert isinstance(filename, str)
        with open(filename, "w") as f:
            json.dump(self.json, f, indent=4, sort_keys=True)

    def size(self):
        return len(self.names)

    def front_addresses(self):
        return self.front

    @staticmethod
    def ip(address):
        assert isinstance(address, str)
        return address.split(":")[0]


class LocalCommittee(Committee):
    """All nodes on localhost, 3 consecutive ports per node from a base
    (benchmark/benchmark/config.py:81-90 convention)."""

    def __init__(self, names, port, bls_pubkeys=None):
        assert isinstance(names, list)
        assert isinstance(port, int)
        size = len(names)
        consensus = [f"127.0.0.1:{port + i}" for i in range(size)]
        front = [f"127.0.0.1:{port + i + size}" for i in range(size)]
        mempool = [f"127.0.0.1:{port + i + 2 * size}" for i in range(size)]
        super().__init__(names, consensus, front, mempool,
                         bls_pubkeys=bls_pubkeys)


def twin_committee(committee, index, port):
    """Committee view for a Twins-style equivocating replica (Bano et
    al.): the SAME identity as replica ``index`` — same keypair, same
    authority entry for every peer — but with its OWN entry's addresses
    remapped to three consecutive ports from ``port``, so the twin
    process binds fresh sockets while signing as its sibling.

    The harness boots the twin with this view and splits the honest
    committee across the two views (half dial the original's ports,
    half the twin's), so BOTH replicas sharing the key receive votes
    and either can propose in the shared identity's leader slots —
    scripted equivocation, which safety must contain (the LogParser's
    conflicting-commit assertion), not merely survive.
    """
    import copy

    assert 0 <= index < len(committee.names)
    name = committee.names[index]
    data = copy.deepcopy(committee.json)
    data["consensus"]["authorities"][name]["address"] = \
        f"127.0.0.1:{port}"
    entry = data["mempool"]["authorities"][name]
    entry["transactions_address"] = f"127.0.0.1:{port + 1}"
    entry["mempool_address"] = f"127.0.0.1:{port + 2}"
    return data


def write_committee_json(data, filename):
    """Write a committee JSON view (twin_committee output) in the same
    format Committee.print uses, so the C++ reader sees no difference."""
    assert isinstance(filename, str)
    with open(filename, "w") as f:
        json.dump(data, f, indent=4, sort_keys=True)


class NodeParameters:
    def __init__(self, json_input):
        inputs = []
        try:
            inputs += [json_input["consensus"]["timeout_delay"]]
            inputs += [json_input["consensus"]["sync_retry_delay"]]
            inputs += [json_input["mempool"]["gc_depth"]]
            inputs += [json_input["mempool"]["sync_retry_delay"]]
            inputs += [json_input["mempool"]["sync_retry_nodes"]]
            inputs += [json_input["mempool"]["batch_size"]]
            inputs += [json_input["mempool"]["max_batch_delay"]]
        except KeyError as e:
            raise ConfigError(f"Malformed parameters: missing key {e}")
        # graftview pacemaker knobs: optional, but when present they must
        # be ints the C++ reader accepts (its own range checks mirror
        # these — a typo'd value must fail at harness time, not as a
        # node-boot crash mid-bench).
        cons = json_input["consensus"]
        for key, lo, hi in (("timeout_backoff_factor_pct", 100, None),
                            ("timeout_backoff_cap", 1, None),
                            ("timeout_jitter_pct", 0, 100),
                            ("timeout_future_horizon", 1, None)):
            v = cons.get(key)
            if v is None:
                continue
            if not isinstance(v, int) or isinstance(v, bool) or v < lo \
                    or (hi is not None and v > hi):
                raise ConfigError(
                    f"{key} must be an int >= {lo}"
                    + (f" and <= {hi}" if hi is not None else "")
                    + f" (got {v!r})")
            inputs += [v]
        if not all(isinstance(x, int) for x in inputs):
            raise ConfigError("Invalid parameters type")
        # graftfleet: tpu_sidecar is one address string (legacy) or an
        # ordered list of them (first = primary, the failover ladder).
        sidecar = json_input.get("tpu_sidecar")
        if sidecar is not None and not isinstance(sidecar, str) and not (
                isinstance(sidecar, list) and sidecar
                and all(isinstance(a, str) for a in sidecar)):
            raise ConfigError("tpu_sidecar must be an address string or a "
                              "non-empty list of address strings")
        tenant = json_input.get("tpu_tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise ConfigError("tpu_tenant must be a string")
        trace = json_input.get("trace")
        if trace is not None and not isinstance(trace, bool):
            raise ConfigError("trace must be a bool")
        # graftdag generalizes the commit walk to any k-chain in [2, 8]
        # (the C++ reader enforces the same range).
        chain = json_input["consensus"].get("chain_depth", 2)
        if not isinstance(chain, int) or isinstance(chain, bool) \
                or not 2 <= chain <= 8:
            raise ConfigError("chain_depth must be an int in [2, 8]")
        # graftdag certified-batch mode: ONE harness knob that must land
        # on BOTH sides of the node (the consensus proposer carries certs
        # and skips the broadcast-ACK wait; the mempool signs availability
        # ACKs and assembles certificates) — a half-set knob would wedge
        # every proposal, so the harness writes/checks them in lockstep.
        dag_c = json_input["consensus"].get("dag", False)
        dag_m = json_input["mempool"].get("dag", False)
        if not isinstance(dag_c, bool) or not isinstance(dag_m, bool):
            raise ConfigError("dag must be a bool")
        if dag_c != dag_m:
            raise ConfigError(
                "dag must be set on both consensus and mempool (lockstep)")
        self.timeout_delay = json_input["consensus"]["timeout_delay"]
        self.json = json_input

    def print(self, filename):
        assert isinstance(filename, str)
        with open(filename, "w") as f:
            json.dump(self.json, f, indent=4, sort_keys=True)

    @classmethod
    def default(cls, tpu_sidecar=None, scheme=None, chain=2, tenant=None,
                dag=False):
        # grafttrace's node-side "trace" flag is not a kwarg here: the
        # harnesses enable it via json.setdefault("trace", True) on
        # whatever parameters the caller built (local.py / remote.py).
        data = {
            "consensus": {"timeout_delay": 5_000, "sync_retry_delay": 10_000},
            "mempool": {
                "gc_depth": 50,
                "sync_retry_delay": 5_000,
                "sync_retry_nodes": 3,
                "batch_size": 500_000,
                "max_batch_delay": 100,
            },
        }
        if chain != 2:
            data["consensus"]["chain_depth"] = chain
        if dag:
            data["consensus"]["dag"] = True
            data["mempool"]["dag"] = True
        if tpu_sidecar:
            data["tpu_sidecar"] = tpu_sidecar
        if tenant:
            data["tpu_tenant"] = tenant
        if scheme:
            data["scheme"] = scheme
        return cls(data)


def add_bls_keys(key_files, committee_names):
    """Generate a BLS keypair per node (scheme=bls deployments): injects
    base64 'bls_secret' into each key file and returns the base64
    96-byte G1 public keys in committee order."""
    import base64

    from ..offchain import bls12381 as bls

    pubkeys = {}
    for filename in key_files:
        with open(filename, "r") as f:
            data = json.load(f)
        # Fresh cryptographic randomness per node — NOT derived from the
        # public name (that would let anyone recompute every secret from
        # the committee file).
        sk, pk = bls.key_gen()
        data["bls_secret"] = base64.b64encode(
            sk.to_bytes(48, "big")).decode()
        with open(filename, "w") as f:
            json.dump(data, f, indent=4, sort_keys=True)
        pubkeys[data["name"]] = base64.b64encode(
            bls.g1_encode(pk)).decode()
    return [pubkeys[name] for name in committee_names]


class BenchParameters:
    def __init__(self, json_input):
        try:
            nodes = json_input["nodes"]
            nodes = nodes if isinstance(nodes, list) else [nodes]
            if not nodes or any(x <= 1 for x in nodes):
                raise ConfigError("Missing or invalid number of nodes")
            rate = json_input["rate"]
            rate = rate if isinstance(rate, list) else [rate]
            if not rate:
                raise ConfigError("Missing input rate")
            self.nodes = [int(x) for x in nodes]
            self.rate = [int(x) for x in rate]
            self.tx_size = int(json_input["tx_size"])
            self.faults = int(json_input["faults"])
            self.duration = int(json_input["duration"])
            self.runs = int(json_input.get("runs", 1))
            self.tpu_sidecar = bool(json_input.get("tpu_sidecar", False))
            self.sidecar_host_crypto = bool(
                json_input.get("sidecar_host_crypto", False))
            self.sidecar_warm_rlc = bool(
                json_input.get("sidecar_warm_rlc", False))
            self.sidecar_mesh = int(json_input.get("sidecar_mesh", 0))
            # graftfleet: boot k sidecars and hand every node the ordered
            # endpoint list (0 or 1 = the single legacy sidecar).
            self.sidecar_fleet = int(json_input.get("sidecar_fleet", 0))
            self.scheme = str(json_input.get("scheme", "ed25519"))
            # graftchaos: a fault-plan spec (path / inline DSL string /
            # event list); parsed + validated by LocalBench.
            self.fault_plan = json_input.get("fault_plan")
            # graftwan: a WAN link-shape spec and a recovery-SLO table
            # (each a path / inline DSL / dict), and the Twins toggle
            # (boot an equivocating sibling of replica 0); parsed +
            # validated by the bench.
            self.wan = json_input.get("wan")
            self.slo = json_input.get("slo")
            self.twins = bool(json_input.get("twins", False))
            # graftingress: signed-transaction ingress.  verify_ingress
            # flips the nodes into admission-verify mode AND the clients
            # into --sign; forge_pct seeds a forgery mix the admission
            # stage must reject; client_shards fans each node's client
            # out over k processes (disjoint user-id / sample-id spaces).
            self.verify_ingress = bool(
                json_input.get("verify_ingress", False))
            self.forge_pct = float(json_input.get("forge_pct", 0.0))
            self.client_shards = int(json_input.get("client_shards", 1))
        except KeyError as e:
            raise ConfigError(f"Malformed bench parameters: missing key {e}")
        except ValueError:
            raise ConfigError("Invalid parameters type")
        if min(self.nodes) <= self.faults:
            raise ConfigError("There should be more nodes than faults")
        if self.client_shards < 1:
            raise ConfigError("client_shards must be >= 1")
        if self.sidecar_fleet < 0:
            raise ConfigError("sidecar_fleet must be >= 0")
        if self.sidecar_fleet > 1 and not (
                self.tpu_sidecar or self.sidecar_host_crypto
                or self.scheme == "bls"):
            # A fleet of sidecars nobody dials is a silent misconfig.
            # host-crypto and bls runs boot a sidecar too, so they may
            # fleet it (LocalBench flips tpu_sidecar on for both).
            raise ConfigError("sidecar_fleet requires tpu_sidecar (or "
                              "sidecar_host_crypto / scheme=bls)")
        if not 0.0 <= self.forge_pct <= 100.0:
            raise ConfigError("forge_pct must be within [0, 100]")
        if self.forge_pct and not self.verify_ingress:
            # Without admission verify, forged txs would commit and
            # silently poison the run's numbers.
            raise ConfigError("forge_pct requires verify_ingress")


class PlotParameters:
    def __init__(self, json_input):
        try:
            faults = json_input["faults"]
            faults = faults if isinstance(faults, list) else [faults]
            self.faults = [int(x) for x in faults] if faults else [0]
            nodes = json_input["nodes"]
            nodes = nodes if isinstance(nodes, list) else [nodes]
            if not nodes:
                raise ConfigError("Missing number of nodes")
            self.nodes = [int(x) for x in nodes]
            self.tx_size = int(json_input["tx_size"])
            max_lat = json_input["max_latency"]
            max_lat = max_lat if isinstance(max_lat, list) else [max_lat]
            if not max_lat:
                raise ConfigError("Missing max latency")
            self.max_latency = [int(x) for x in max_lat]
        except KeyError as e:
            raise ConfigError(f"Malformed plot parameters: missing key {e}")
        except ValueError:
            raise ConfigError("Invalid parameters type")


def ordered(data):
    return OrderedDict(sorted(data.items()))

from .config import (  # noqa: F401
    BenchParameters,
    Committee,
    ConfigError,
    Key,
    LocalCommittee,
    NodeParameters,
    PlotParameters,
)
from .logs import LogParser, ParseError  # noqa: F401
from .utils import BenchError, PathMaker, Print  # noqa: F401

"""Matplotlib plots over aggregated series
(benchmark/benchmark/plot.py:16-164 capability: latency-vs-throughput,
tps-vs-committee-size, robustness; tps↔bps twin axis).
"""

from __future__ import annotations

from glob import glob
from itertools import cycle
from os.path import join
from re import findall, search

from .utils import PathMaker


class PlotError(Exception):
    pass


class Ploter:
    def __init__(self, width=6.4, height=4.8):
        import matplotlib

        matplotlib.use("Agg")  # headless
        import matplotlib.pyplot as plt

        plt.figure(figsize=(width, height))
        self.plt = plt

    @staticmethod
    def _natural_keys(text):
        def try_cast(t):
            return int(t) if t.isdigit() else t
        return [try_cast(c) for c in findall(r"(\d+|\D+)", text)]

    @staticmethod
    def _tps2bps(x, tx_size):
        return x * tx_size / 1e6

    @staticmethod
    def _bps2tps(x, tx_size):
        return x * 1e6 / tx_size

    def _measurements(self, data):
        values = findall(r"Variable value: X=(\d+)", data)
        tps = findall(r"TPS: (\d+) \+/- (\d+)", data)
        latency = findall(r"Latency: (\d+) \+/- (\d+)", data)
        if not (len(values) == len(tps) == len(latency)):
            raise PlotError("Unequal number of x and y values")
        return (
            [int(x) for x in values],
            [int(x) for x, _ in tps],
            [int(s) for _, s in tps],
            [int(x) for x, _ in latency],
            [int(s) for _, s in latency],
        )

    def _plot(self, x_label, y_label, y_axis, z_axis, type,
              tps_y_axis=False):
        self.plt.clf()
        markers = cycle(["o", "v", "s", "d", "^"])
        files = sorted(glob(join(PathMaker.plot_path(), f"{type}*.txt")),
                       key=self._natural_keys)
        if not files:
            raise PlotError(f"no aggregated data for {type}")
        tx_sizes = set()
        for filename in files:
            with open(filename, "r") as f:
                data = f.read()
            m = search(r"Transaction size: (\d+)", data)
            if m:
                tx_sizes.add(int(m.group(1)))
            values, tps, tps_std, lat, lat_std = self._measurements(data)
            x = values
            y, y_err = y_axis(tps, tps_std, lat, lat_std)
            label = z_axis(data)
            self.plt.errorbar(x, y, yerr=y_err, label=label,
                              marker=next(markers), capsize=3, linestyle="-")
        self.plt.legend(loc="best", fontsize="small")
        self.plt.xlabel(x_label)
        self.plt.ylabel(y_label)
        self.plt.grid(True, alpha=0.3)
        if tps_y_axis and len(tx_sizes) == 1:
            # Twin tps<->MB/s axis (the reference's plot.py:46-54). Only
            # drawn when every series shares one tx size — a mixed plot
            # would mislabel the MB/s scale for all but one series.
            tx_size = tx_sizes.pop()
            self.plt.gca().secondary_yaxis(
                "right",
                functions=(
                    lambda v: self._tps2bps(v, tx_size),
                    lambda v: self._bps2tps(v, tx_size),
                )).set_ylabel("Throughput (MB/s)")
        for ext in ("pdf", "png"):
            self.plt.savefig(PathMaker.plot_file(type, ext),
                             bbox_inches="tight")

    @staticmethod
    def _committee_label(data):
        m = search(r"Committee size: (\d+)", data)
        f = search(r"Faults: (\d+)", data)
        label = f"{m.group(1)} nodes" if m else "?"
        if f and int(f.group(1)):
            label += f" ({f.group(1)} faulty)"
        if search(r"Scripted chaos/WAN: True", data):
            # chaos runs aggregate apart from clean ones (no-masquerade
            # contract); the legend must keep the two series apart too
            label += " [chaos]"
        return label

    def plot_latency(self):
        self._plot(
            "Throughput (tx/s)", "Latency (ms)",
            lambda tps, tps_std, lat, lat_std: (lat, lat_std),
            self._committee_label, "latency")

    def plot_robustness(self):
        self._plot(
            "Input rate (tx/s)", "Throughput (tx/s)",
            lambda tps, tps_std, lat, lat_std: (tps, tps_std),
            self._committee_label, "robustness", tps_y_axis=True)

    def plot_tps(self):
        def label(data):
            m = search(r"Max latency: (\d+)", data)
            return f"max latency {m.group(1)} ms" if m else "tps"
        self._plot("Committee size", "Throughput (tx/s)",
                   lambda tps, tps_std, lat, lat_std: (tps, tps_std),
                   label, "tps-scalability", tps_y_axis=True)

    def plot_trace(self, trace_path=None):
        """grafttrace: per-stage latency histograms from the run's
        ``logs/trace.json`` (Chrome trace events; obs/trace.py).  One
        panel per segment/stage that has samples — the visual form of
        the "Commit critical path" parser note."""
        import json

        path = trace_path or PathMaker.trace_file()
        try:
            with open(path) as f:
                chrome = json.load(f)
        except (OSError, ValueError):
            raise PlotError(f"no trace artifact at {path} (run a traced "
                            "bench first)")
        by_stage = {}
        for ev in chrome.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            by_stage.setdefault(ev["name"], []).append(
                float(ev.get("dur", 0)) / 1e3)  # us -> ms
        by_stage = {k: v for k, v in by_stage.items() if v}
        if not by_stage:
            raise PlotError("trace.json has no duration events")
        fig, axes = self.plt.subplots(
            1, len(by_stage), squeeze=False,
            figsize=(3.2 * len(by_stage), 3.2))
        for ax, (stage, durs) in zip(axes[0], sorted(by_stage.items())):
            ax.hist(durs, bins=min(30, max(5, len(durs) // 2)))
            ax.set_title(f"{stage} (n={len(durs)})", fontsize=8)
            ax.set_xlabel("Latency (ms)", fontsize=7)
            ax.grid(True, alpha=0.3)
        axes[0][0].set_ylabel("Spans")
        for ext in ("pdf", "png"):
            fig.savefig(PathMaker.plot_file("trace-hist", ext),
                        bbox_inches="tight")
        self.plt.close(fig)

    def plot_metrics(self, metrics_path=None):
        """grafttrace: the sampled OP_STATS time series as throughput /
        queue-wait curves (``logs/metrics.jsonl``), with failed ticks —
        a chaos-killed sidecar's telemetry blackout — marked, so a
        recovery transition is visible as a curve, not a scalar.

        graftscope: when the series carries the C++ node's per-replica
        METRICS records, a second panel overlays every replica's sampled
        commit rate (the straggler-detection curves), with the same
        blackout markers — a replica diverging from the committee is a
        visibly lagging line, not just a parser note."""
        from ..obs import read_samples, split_samples

        path = metrics_path or PathMaker.metrics_file()
        samples, _ = read_samples(path)
        if len(samples) < 2:
            raise PlotError(f"fewer than two metrics samples at {path}")
        sidecar, node = split_samples(samples)
        t0 = min(s["t"] for s in samples)
        xs_ok, sig_rate, wait_p99 = [], [], []
        xs_bad = []
        prev = None
        for s in sorted(sidecar, key=lambda s: s["t"]):
            if not s.get("ok"):
                xs_bad.append(s["t"] - t0)
                prev = None  # a blackout breaks the rate delta chain
                continue
            stats = s.get("stats") or {}
            sigs = stats.get("sigs_launched", 0)
            if prev is not None and s["t"] > prev[0]:
                xs_ok.append(s["t"] - t0)
                sig_rate.append((sigs - prev[1]) / (s["t"] - prev[0]))
                wait = (stats.get("queue_wait") or {}).get("latency") or {}
                wait_p99.append(wait.get("p99_ms", 0))
            prev = (s["t"], sigs)
        by_replica = {}
        for s in sorted(node, key=lambda s: s["t"]):
            rate = (s.get("metrics") or {}).get("commit_rate")
            if isinstance(rate, (int, float)):
                xs, ys = by_replica.setdefault(s["node"], ([], []))
                xs.append(s["t"] - t0)
                ys.append(rate)
        self.plt.clf()
        nrows = 1 + (1 if by_replica else 0)
        fig, axes = self.plt.subplots(
            nrows, 1, squeeze=False, sharex=True,
            figsize=(6.4, 4.8 if nrows == 1 else 7.2))
        ax = axes[0][0]
        ax.plot(xs_ok, sig_rate, marker="o", markersize=3,
                label="verify throughput (sigs/s)")
        ax.set_ylabel("Sigs/s launched")
        ax2 = ax.twinx()
        ax2.plot(xs_ok, wait_p99, color="tab:orange", marker="s",
                 markersize=3, label="latency queue-wait p99 (ms)")
        ax2.set_ylabel("Queue wait p99 (ms)")
        # Blackout markers BEFORE the legends are assembled, so the
        # "failed sample" entry actually appears on chaos runs.
        for r in range(nrows):
            for i, x in enumerate(xs_bad):
                axes[r][0].axvline(
                    x, color="red", alpha=0.4, linestyle="--",
                    label="failed sample (sidecar down)"
                    if i == 0 and r == 0 else None)
        lines, labels = ax.get_legend_handles_labels()
        l2, lb2 = ax2.get_legend_handles_labels()
        ax.legend(lines + l2, labels + lb2, loc="best", fontsize="small")
        ax.grid(True, alpha=0.3)
        if by_replica:
            axr = axes[1][0]
            markers = cycle(["o", "v", "s", "d", "^"])
            for host, (xs, ys) in sorted(by_replica.items()):
                axr.plot(xs, ys, marker=next(markers), markersize=2,
                         linewidth=1, label=host)
            axr.set_ylabel("Commit rate (blocks/s)")
            axr.legend(loc="best", fontsize="x-small")
            axr.grid(True, alpha=0.3)
        axes[-1][0].set_xlabel("Run time (s)")
        for ext in ("pdf", "png"):
            fig.savefig(PathMaker.plot_file("metrics", ext),
                        bbox_inches="tight")
        self.plt.close(fig)

    def plot_matrix(self):
        """graftwan matrix heatmap: one nodes×rate panel of end-to-end
        TPS per (faults, tx_size) group from ``plots/matrix.json``
        (LogAggregator.print_matrix).  Chaos/WAN cells are hatched so a
        faulted or shaped number is visually distinct from a clean-LAN
        one; an SLO breach gets a red edge."""
        import json

        path = join(PathMaker.plot_path(), "matrix.json")
        try:
            with open(path) as f:
                groups = json.load(f)
        except (OSError, ValueError):
            raise PlotError("no matrix.json (run aggregate first)")
        groups = {k: g for k, g in groups.items()
                  if g.get("cells") and len(g["cells"]) >= 2}
        if not groups:
            raise PlotError("matrix has fewer than two cells")
        self.plt.clf()
        fig, axes = self.plt.subplots(
            1, len(groups), squeeze=False,
            figsize=(6.4 * len(groups), 4.8))
        for ax, (key, group) in zip(axes[0], sorted(groups.items())):
            nodes, rates = group["nodes"], group["rates"]
            grid = [[float("nan")] * len(rates) for _ in nodes]
            for (ni, n) in enumerate(nodes):
                for (ri, r) in enumerate(rates):
                    cell = group["cells"].get(f"{n}-{r}")
                    if cell is None:
                        continue
                    grid[ni][ri] = cell["tps"]
                    label = f"{cell['tps']:,}\n{cell['latency_ms']:,} ms"
                    chaos = cell.get("chaos")
                    if chaos:
                        label += "\nC!" if chaos.get("slo_fail") else "\nC"
                    ax.text(ri, ni, label, ha="center", va="center",
                            fontsize=7)
                    if chaos:
                        from matplotlib.patches import Rectangle

                        ax.add_patch(Rectangle(
                            (ri - 0.5, ni - 0.5), 1, 1, fill=False,
                            hatch="//",
                            edgecolor="red" if chaos.get("slo_fail")
                            else "gray", linewidth=1.5))
            im = ax.imshow(grid, aspect="auto", cmap="viridis")
            ax.set_xticks(range(len(rates)),
                          [f"{r:,}" for r in rates], fontsize=7)
            ax.set_yticks(range(len(nodes)), nodes)
            ax.set_xlabel("Input rate (tx/s)")
            ax.set_ylabel("Committee size")
            ax.set_title(f"faults={group['faults']} "
                         f"tx={group['tx_size']}B (TPS; C=chaos/WAN)")
            fig.colorbar(im, ax=ax, shrink=0.8)
        for ext in ("pdf", "png"):
            fig.savefig(PathMaker.plot_file("matrix", ext),
                        bbox_inches="tight")
        self.plt.close(fig)

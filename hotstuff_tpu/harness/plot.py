"""Matplotlib plots over aggregated series
(benchmark/benchmark/plot.py:16-164 capability: latency-vs-throughput,
tps-vs-committee-size, robustness; tps↔bps twin axis).
"""

from __future__ import annotations

from glob import glob
from itertools import cycle
from os.path import join
from re import findall, search

from .utils import PathMaker


class PlotError(Exception):
    pass


class Ploter:
    def __init__(self, width=6.4, height=4.8):
        import matplotlib

        matplotlib.use("Agg")  # headless
        import matplotlib.pyplot as plt

        plt.figure(figsize=(width, height))
        self.plt = plt

    @staticmethod
    def _natural_keys(text):
        def try_cast(t):
            return int(t) if t.isdigit() else t
        return [try_cast(c) for c in findall(r"(\d+|\D+)", text)]

    @staticmethod
    def _tps2bps(x, tx_size):
        return x * tx_size / 1e6

    @staticmethod
    def _bps2tps(x, tx_size):
        return x * 1e6 / tx_size

    def _measurements(self, data):
        values = findall(r"Variable value: X=(\d+)", data)
        tps = findall(r"TPS: (\d+) \+/- (\d+)", data)
        latency = findall(r"Latency: (\d+) \+/- (\d+)", data)
        if not (len(values) == len(tps) == len(latency)):
            raise PlotError("Unequal number of x and y values")
        return (
            [int(x) for x in values],
            [int(x) for x, _ in tps],
            [int(s) for _, s in tps],
            [int(x) for x, _ in latency],
            [int(s) for _, s in latency],
        )

    def _plot(self, x_label, y_label, y_axis, z_axis, type,
              tps_y_axis=False):
        self.plt.clf()
        markers = cycle(["o", "v", "s", "d", "^"])
        files = sorted(glob(join(PathMaker.plot_path(), f"{type}*.txt")),
                       key=self._natural_keys)
        if not files:
            raise PlotError(f"no aggregated data for {type}")
        tx_sizes = set()
        for filename in files:
            with open(filename, "r") as f:
                data = f.read()
            m = search(r"Transaction size: (\d+)", data)
            if m:
                tx_sizes.add(int(m.group(1)))
            values, tps, tps_std, lat, lat_std = self._measurements(data)
            x = values
            y, y_err = y_axis(tps, tps_std, lat, lat_std)
            label = z_axis(data)
            self.plt.errorbar(x, y, yerr=y_err, label=label,
                              marker=next(markers), capsize=3, linestyle="-")
        self.plt.legend(loc="best", fontsize="small")
        self.plt.xlabel(x_label)
        self.plt.ylabel(y_label)
        self.plt.grid(True, alpha=0.3)
        if tps_y_axis and len(tx_sizes) == 1:
            # Twin tps<->MB/s axis (the reference's plot.py:46-54). Only
            # drawn when every series shares one tx size — a mixed plot
            # would mislabel the MB/s scale for all but one series.
            tx_size = tx_sizes.pop()
            self.plt.gca().secondary_yaxis(
                "right",
                functions=(
                    lambda v: self._tps2bps(v, tx_size),
                    lambda v: self._bps2tps(v, tx_size),
                )).set_ylabel("Throughput (MB/s)")
        for ext in ("pdf", "png"):
            self.plt.savefig(PathMaker.plot_file(type, ext),
                             bbox_inches="tight")

    @staticmethod
    def _committee_label(data):
        m = search(r"Committee size: (\d+)", data)
        f = search(r"Faults: (\d+)", data)
        label = f"{m.group(1)} nodes" if m else "?"
        if f and int(f.group(1)):
            label += f" ({f.group(1)} faulty)"
        return label

    def plot_latency(self):
        self._plot(
            "Throughput (tx/s)", "Latency (ms)",
            lambda tps, tps_std, lat, lat_std: (lat, lat_std),
            self._committee_label, "latency")

    def plot_robustness(self):
        self._plot(
            "Input rate (tx/s)", "Throughput (tx/s)",
            lambda tps, tps_std, lat, lat_std: (tps, tps_std),
            self._committee_label, "robustness", tps_y_axis=True)

    def plot_tps(self):
        def label(data):
            m = search(r"Max latency: (\d+)", data)
            return f"max latency {m.group(1)} ms" if m else "tps"
        self._plot("Committee size", "Throughput (tx/s)",
                   lambda tps, tps_std, lat, lat_std: (tps, tps_std),
                   label, "tps-scalability", tps_y_axis=True)

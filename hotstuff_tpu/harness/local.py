"""Local benchmark: run a full committee + clients (+ optional TPU verify
sidecar) on this machine and mine the logs for TPS/latency.

Capability mirror of benchmark/benchmark/local.py:12-120: kill stale
processes, compile, generate keys/committee/parameters, boot nodes minus
`faults` (crash faults = nodes never booted), boot one client per node at
rate/N, run for `duration`, parse logs. Processes are plain subprocesses
with per-process log redirection (the reference used tmux panes for the
same effect).
"""

from __future__ import annotations

import os
import signal
import subprocess
from time import monotonic, sleep

from .commands import CommandMaker
from .config import Key, LocalCommittee, NodeParameters
from .logs import LogParser, ParseError
from .utils import BenchError, PathMaker, Print


class LocalBench:
    BASE_PORT = 9000
    SIDECAR_PORT = 7100
    # graftwan: the userspace WanProxy for a shaped node->sidecar link
    # binds here; the parameters file points the nodes at it.
    WAN_SIDECAR_PORT = 7101
    # Twins: the equivocating replica binds three consecutive ports from
    # here (clear of the committee's BASE_PORT + 3*n block).
    TWIN_BASE_PORT = 9900
    # grafttrace: OP_STATS sampling cadence during the run window.  1 Hz
    # keeps even a minimum-duration run at a handful of in-window
    # samples while costing the sidecar one connection thread per tick.
    METRICS_INTERVAL_S = 1.0

    def __init__(self, bench_parameters, node_parameters=None):
        self.nodes = bench_parameters.nodes[0]
        self.rate = bench_parameters.rate[0]
        self.tx_size = bench_parameters.tx_size
        self.faults = bench_parameters.faults
        self.duration = bench_parameters.duration
        self.tpu_sidecar = getattr(bench_parameters, "tpu_sidecar", False)
        # graftfleet: sidecar_fleet k > 1 boots k sidecars on consecutive
        # ports (SIDECAR_PORT + i) and hands every node the ORDERED
        # endpoint list — the C++ TpuVerifier's failover ladder.  0/1 is
        # the legacy single-sidecar run, byte-identical artifacts.
        self.sidecar_fleet = int(getattr(
            bench_parameters, "sidecar_fleet", 0) or 0)
        self.sidecar_host_crypto = getattr(
            bench_parameters, "sidecar_host_crypto", False)
        self.sidecar_warm_rlc = getattr(
            bench_parameters, "sidecar_warm_rlc", False)
        self.sidecar_mesh = int(getattr(
            bench_parameters, "sidecar_mesh", 0) or 0)
        if self.sidecar_host_crypto:
            self.tpu_sidecar = True  # host-crypto still runs the sidecar
        self.scheme = getattr(bench_parameters, "scheme", "ed25519")
        if self.scheme == "bls":
            self.tpu_sidecar = True  # no host pairing in the C++ plane
        # graftingress: signed-transaction ingress knobs (config.py
        # BenchParameters validated the ranges).
        self.verify_ingress = bool(
            getattr(bench_parameters, "verify_ingress", False))
        self.forge_pct = float(
            getattr(bench_parameters, "forge_pct", 0.0) or 0.0)
        self.client_shards = max(1, int(
            getattr(bench_parameters, "client_shards", 1) or 1))
        # graftfleet: a fleet run hands nodes the ordered endpoint list
        # (primary first) plus a tenant id for the protocol-v6 HELLO;
        # the single-sidecar run keeps the legacy one-address string.
        if self.tpu_sidecar and self.sidecar_fleet > 1:
            sidecar_addr = [f"127.0.0.1:{self.SIDECAR_PORT + i}"
                            for i in range(self.sidecar_fleet)]
        elif self.tpu_sidecar:
            sidecar_addr = f"127.0.0.1:{self.SIDECAR_PORT}"
        else:
            sidecar_addr = None
        self.node_parameters = node_parameters or NodeParameters.default(
            tpu_sidecar=sidecar_addr,
            scheme=self.scheme if self.scheme != "ed25519" else None,
            tenant="node" if self.sidecar_fleet > 1 else None)
        if self.verify_ingress:
            # The node-side admission-verify stage rides the mempool
            # parameters straight into the C++ from_json reader;
            # setdefault, so caller-provided parameters win.
            self.node_parameters.json.setdefault(
                "mempool", {}).setdefault("verify_ingress", True)
        # grafttrace: benched runs always trace (the span lines are one
        # relaxed atomic load when the committee config disables them,
        # and the critical-path breakdown is what makes the run's
        # numbers attributable).  setdefault, so an explicit
        # "trace": false in caller-provided parameters wins.
        self.node_parameters.json.setdefault("trace", True)
        self._procs = []
        self._degraded = False
        # graftchaos: per-node boot info + the sidecar boot command are
        # tracked so the fault injector can SIGKILL/SIGSTOP groups and
        # reboot on the same store/log (harness/faults.py).
        self._node_procs = {}
        self._node_cmds = {}
        self._sidecar_proc = None
        self._sidecar_cmd = None
        # graftfleet: per-index boot info ({ix: proc} / {ix: (cmd, log)});
        # index 0 is mirrored into the legacy attributes above so the
        # single-sidecar injector/test surface stays byte-compatible.
        self._sidecar_procs = {}
        self._sidecar_cmds = {}
        # graftsurge: {i: (address, tx_size, rate_share)} for the booted
        # clients, so a plan's client:<i> surge event can boot an extra
        # generator at a multiple of the baseline (harness/faults.py).
        self._client_targets = {}
        # graftview: committee names in BOOT order — the leader-cascade
        # injector maps round-robin leader slots (sorted-key order, the
        # C++ LeaderElector's rule) back to the node index to SIGKILL.
        self._node_names = []
        fp = getattr(bench_parameters, "fault_plan", None)
        if fp:
            from ..chaos import PlanError, parse_plan

            try:
                self.fault_plan = parse_plan(fp)
            except PlanError as e:
                raise BenchError("Invalid fault plan", e)
        else:
            self.fault_plan = None
        # graftwan: WAN spec + SLO table, parsed/validated NOW (same
        # fail-before-compile contract as the fault plan).  Locally the
        # spec is realized by WanProxy instances; _check_wan below
        # rejects links no proxy can stand in for.
        self._wan_proxies = {}
        self._twin_proc = None
        wan = getattr(bench_parameters, "wan", None)
        if wan:
            from ..chaos import WanError, parse_wan

            try:
                self.wan = parse_wan(wan)
            except WanError as e:
                raise BenchError("Invalid WAN spec", e)
        else:
            self.wan = None
        slo = getattr(bench_parameters, "slo", None)
        from ..chaos import SloError, parse_slos

        try:
            self.slos = parse_slos(slo)
        except SloError as e:
            raise BenchError("Invalid SLO table", e)
        self.twins = bool(getattr(bench_parameters, "twins", False))
        if self.wan is not None and any(
                link.dst == "sidecar" for link in self.wan.links):
            if not self.tpu_sidecar:
                raise BenchError(
                    "WAN spec shapes the sidecar link but this run "
                    "boots no sidecar (pass --tpu-sidecar / "
                    "--sidecar-host-crypto)", None)
            if self.sidecar_fleet > 1:
                # The fleet binds consecutive ports from SIDECAR_PORT,
                # so sidecar 1 lands exactly on the shared proxy port
                # (WAN_SIDECAR_PORT = SIDECAR_PORT + 1) — and one proxy
                # cannot front an ordered endpoint LIST anyway.
                raise BenchError(
                    "WAN sidecar links are single-sidecar only: the "
                    "fleet's consecutive ports collide with the shared "
                    "proxy port (shape fleet links on the remote "
                    "harness)", None)
            # Nodes reach the sidecar THROUGH the proxy: the link's
            # shape applies to every verify RPC, and a link:<name>
            # partition event black-holes the accelerator service.
            self.node_parameters.json["tpu_sidecar"] = \
                f"127.0.0.1:{self.WAN_SIDECAR_PORT}"

    def _background_run(self, command, log_file, append=False):
        name = command.split()[0]
        # stdout -> /dev/null: children must not inherit the harness's
        # stdout pipe, or an orphaned node keeps a killed harness's caller
        # blocked on that pipe forever (logs go to stderr).
        cmd = f"{command} > /dev/null 2{'>>' if append else '>'} {log_file}"
        # Python children (the sidecar) must find hotstuff_tpu regardless
        # of the harness cwd — `python -m` in the child does not inherit
        # the parent interpreter's implicit cwd sys.path entry.
        env = os.environ.copy()
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        # graftkern: the sidecar child gets the repo-local persistent
        # compile cache by default, so warm boots deserialize programs
        # instead of recompiling (the same dir bench.py and the warmup
        # manifest use).  An exported HOTSTUFF_TPU_XLA_CACHE always wins
        # — including an EMPTY value, which disables the cache.
        if "HOTSTUFF_TPU_XLA_CACHE" not in env:
            env["HOTSTUFF_TPU_XLA_CACHE"] = os.path.join(
                pkg_root, "results", "compile_cache", "xla")
        proc = subprocess.Popen(
            ["/bin/sh", "-c", cmd], preexec_fn=os.setsid, env=env)
        self._procs.append((name, proc))
        return proc

    def _wait_sidecar_ready(self, deadline_s=300, index=None):
        """Block until the sidecar answers a PING (it binds post-warmup, so
        the first accepted connection implies the jit cache is hot).
        graftfleet: ``index`` picks fleet member i (port SIDECAR_PORT+i,
        per-index log file); None is the legacy single sidecar."""
        from ..sidecar.client import SidecarClient

        port = self.SIDECAR_PORT + (index or 0)
        who = "Sidecar" if index is None else f"Sidecar {index}"
        start = monotonic()
        while True:
            try:
                with SidecarClient(port=port, timeout=5.0) as client:
                    client.ping()
                Print.info(f"{who} ready after "
                           f"{monotonic() - start:.0f}s (warmup done)")
                return
            except (OSError, ConnectionError):
                if monotonic() - start > deadline_s:
                    raise BenchError(
                        f"TPU sidecar failed to become ready; see "
                        f"{PathMaker.sidecar_log_file(index)}",
                        TimeoutError(f"{deadline_s}s elapsed"))
                sleep(0.5)

    def _kill_nodes(self):
        for _, proc in self._procs:
            try:
                pgid = os.getpgid(proc.pid)
                os.killpg(pgid, signal.SIGTERM)
                # A chaos-paused (SIGSTOPped) group only sees the SIGTERM
                # once continued; always chase with SIGCONT so teardown
                # can never leave a stopped orphan holding the ports.
                os.killpg(pgid, signal.SIGCONT)
            except (ProcessLookupError, PermissionError):
                pass
        self._procs = []
        self._node_procs = {}
        self._sidecar_proc = None
        self._sidecar_procs = {}
        self._twin_proc = None
        # Stale-state discipline (benchmark/local.py:31-37): also sweep by
        # pattern for processes from previous runs this harness no longer
        # tracks — including the sidecar, which a wedged device can leave
        # hung past its process group's SIGTERM.  Each pkill is exec'd
        # directly: under `sh -c "pkill ...; pkill ..."` the first pattern
        # matches the wrapper shell's own cmdline and kills the rest of
        # the chain before it runs.
        for args in (["pkill", "-f", r"\./node run"],
                     ["pkill", "-f", r"\./client 127"],
                     ["pkill", "-9", "-f", r"hotstuff_tpu\.sidecar"]):
            subprocess.run(args, check=False, capture_output=True)

    def _sidecar_deadline_s(self, host_crypto: bool) -> int:
        """Readiness budget: the BLS pairing program is a multi-minute
        first compile on the device (cached across restarts via the XLA
        compilation cache); host-crypto warmup compiles nothing."""
        if host_crypto:
            return 120
        return 900 if self.scheme == "bls" else 300

    def _boot_sidecar(self, host_crypto: bool, index=None):
        """Boot the verify sidecar and wait for readiness.  If the device
        path never comes up (wedged TPU tunnel: jit warmup blocks forever),
        kill it and degrade to a --host-crypto sidecar with a loud warning
        — a host-mode result beats a dead bench.

        graftfleet: ``index=i`` boots fleet member i on SIDECAR_PORT+i
        with a per-index log file and does NOT wait or degrade — the
        fleet wrapper (:meth:`_boot_sidecars`) waits on every member and
        degrades the whole fleet together (a half-host fleet would hand
        the failover ladder asymmetric masks)."""
        mode = " (HOST crypto)" if host_crypto else ""
        who = "" if index is None else f" {index}"
        Print.info(f"Booting TPU verify sidecar{who}...{mode}")
        warm_bls = ""
        if self.scheme == "bls":
            # Warm both BLS shapes: the 2-pairing QC check and the
            # quorum-size multi-digest TC check (one compiled program per
            # vote count; unwarmed counts verify on host).  The vote count
            # MUST use the node's own quorum formula (2n/3+1 with unit
            # stakes, native/src/consensus/config.hpp — NOT 2f+1 from
            # n=3f+1, which disagrees for n not of that form, e.g. n=20)
            # or every TC verify falls back to host pairing mid-traffic.
            # The certificate-minimality guard (messages.cpp) rejects
            # over-quorum TCs, so this one shape covers every TC a
            # well-formed run can carry.
            quorum = 2 * self.nodes // 3 + 1
            warm_bls = f" --warm-bls --warm-bls-multi {quorum}"
        hc = " --host-crypto" if host_crypto else ""
        # RLC warmup is opt-in (each bucket is another boot-time compile,
        # though cached across restarts) and meaningless in host mode.
        warm_rlc = " --warm-rlc" \
            if getattr(self, "sidecar_warm_rlc", False) and not host_crypto \
            else ""
        # Mesh mode: shard verify launches over an N-device mesh, with
        # the sharded one-MSM warmup so coalesced QC batches route
        # through the rlc_sharded engine path from the first block.
        mesh = ""
        if int(getattr(self, "sidecar_mesh", 0) or 0) > 1 \
                and not host_crypto:
            mesh = f" --mesh {self.sidecar_mesh} --warm-rlc-sharded"
        # The chaos hook binds only when a fault plan can reach it; the
        # committee/rate parameters size the scheduler's admission caps
        # (sidecar/sched/scheduler.size_queue_caps) instead of the static
        # defaults.
        chaos = " --chaos" if getattr(self, "fault_plan", None) else ""
        # grafttrace: sidecar stage spans ride a JSONL file next to the
        # logs (appended across chaos restarts, like the log itself).
        trace = f" --trace {PathMaker.sidecar_spans_file()}"
        port = self.SIDECAR_PORT + (index or 0)
        log = PathMaker.sidecar_log_file(index)
        cmd = (f"python -m hotstuff_tpu.sidecar "
               f"--port {port}"
               f" --committee {self.nodes} --client-rate {self.rate}"
               f"{warm_bls}{warm_rlc}{mesh}{hc}{chaos}{trace}")
        # The degraded reboot appends to the log: the dead device
        # sidecar's output is the evidence needed to diagnose the wedge.
        proc = self._background_run(cmd, log, append=self._degraded)
        ix = 0 if index is None else index
        if not isinstance(getattr(self, "_sidecar_procs", None), dict):
            self._sidecar_procs = {}
            self._sidecar_cmds = {}
        self._sidecar_cmds[ix] = (cmd, log)
        self._sidecar_procs[ix] = proc
        if ix == 0:
            self._sidecar_cmd = (cmd, log)
            self._sidecar_proc = proc
        if index is not None:
            return  # the fleet wrapper waits on the whole fleet
        try:
            self._wait_sidecar_ready(
                deadline_s=self._sidecar_deadline_s(host_crypto))
        except BenchError:
            self._kill_nodes()
            if host_crypto:
                raise
            Print.warn(
                "TPU sidecar never became ready (wedged device tunnel?); "
                "DEGRADING to a host-crypto sidecar. This run will NOT "
                "measure the device verify path.")
            self._degraded = True
            self._boot_sidecar(host_crypto=True)

    def _boot_sidecars(self, host_crypto: bool):
        """Boot the sidecar fleet (sidecar_fleet members on consecutive
        ports) and wait for every member; degrade the WHOLE fleet to
        host-crypto if any member wedges.  Fleet size <= 1 is the legacy
        single-sidecar boot, unchanged."""
        k = self.sidecar_fleet
        if k <= 1:
            self._boot_sidecar(host_crypto=host_crypto)
            return
        Print.info(f"Booting sidecar fleet ({k} endpoints)...")
        for i in range(k):
            self._boot_sidecar(host_crypto, index=i)
        try:
            # Warmup compiles overlap (the processes boot concurrently;
            # the persistent XLA cache dedups the work), so one budget
            # covers each member's wait in turn.
            deadline = self._sidecar_deadline_s(host_crypto)
            for i in range(k):
                self._wait_sidecar_ready(deadline_s=deadline, index=i)
        except BenchError:
            self._kill_nodes()
            if host_crypto:
                raise
            Print.warn(
                "A fleet sidecar never became ready (wedged device "
                "tunnel?); DEGRADING the whole fleet to host-crypto "
                "sidecars. This run will NOT measure the device verify "
                "path.")
            self._degraded = True
            self._boot_sidecars(host_crypto=True)

    def _start_metrics_sampler(self):
        """Poll OP_STATS at a fixed interval for the whole run window
        (obs/sampler.py), appending the time series to logs/metrics.jsonl
        — so throughput/queue-wait over time is plottable and a
        chaos-killed sidecar's telemetry survives as the last good
        sample.  The connection persists across ticks with reconnect-
        on-failure (obs/sampler.persistent_fetch): the sampler still
        outlives a sidecar kill/restart — a dead socket costs one
        ok-false tick and the next tick re-dials — without paying (and
        measuring) a TCP dial on every healthy 1 Hz sample."""
        if not self.tpu_sidecar:
            return None
        from ..obs import MetricsSampler
        from ..obs.sampler import persistent_fetch
        from ..sidecar.client import SidecarClient

        if self.sidecar_fleet > 1:
            # graftfleet: one persistent connection per endpoint; every
            # sample carries its endpoint tag so a kill of sidecar i
            # shows as ok-false ticks for THAT endpoint while the rest
            # of the fleet's series keeps flowing.
            fetches = []
            for i in range(self.sidecar_fleet):
                port = self.SIDECAR_PORT + i
                fetches.append((
                    f"127.0.0.1:{port}",
                    persistent_fetch(
                        lambda p=port: SidecarClient(port=p, timeout=5.0))))
            fetch = fetches
        else:
            fetch = persistent_fetch(
                lambda: SidecarClient(port=self.SIDECAR_PORT, timeout=5.0))
        self._sampler = MetricsSampler(
            fetch,
            PathMaker.metrics_file(),
            interval_s=self.METRICS_INTERVAL_S)
        return self._sampler.start()

    def _fetch_sidecar_stats(self):
        """Write the sidecar's OP_STATS snapshot next to the logs; best
        effort — but a sidecar that died before teardown (chaos kill)
        no longer loses its telemetry silently: the periodic sampler's
        last good snapshot becomes the fallback, marked so the parser
        says where the numbers came from."""
        import json

        from ..sidecar.client import SidecarClient

        k = max(1, int(getattr(self, "sidecar_fleet", 0) or 0))
        for i in range(k):
            port = self.SIDECAR_PORT + i
            index = None if k == 1 else i
            endpoint = f"127.0.0.1:{port}"
            try:
                with SidecarClient(port=port, timeout=10.0) as client:
                    stats = client.stats()
            except (OSError, ConnectionError, ValueError) as e:
                sampler = getattr(self, "_sampler", None)
                last = None if sampler is None else (
                    sampler.last if k == 1
                    else sampler.last_by_endpoint.get(endpoint))
                if last is None:
                    Print.warn(f"Could not fetch sidecar scheduler stats "
                               f"({endpoint}): {e}")
                    continue
                sampled_at, snap = last
                Print.warn(f"Sidecar stats fetch failed ({endpoint}: {e}); "
                           "falling back to the last periodic sample")
                stats = dict(snap, _from_sample_at=sampled_at)
            if index is not None:
                stats = dict(stats, _endpoint=endpoint)
            with open(PathMaker.sidecar_stats_file(index), "w") as f:
                json.dump(stats, f)

    def _check_fault_plan(self):
        """Reject an unexecutable plan BEFORE anything boots: every input
        (duration, committee, faults, sidecar mode, timeout) is known at
        construction time, and a plan targeting a replica that will never
        exist must not cost a multi-minute compile+warmup first."""
        if self.fault_plan is None or not self.fault_plan.events:
            return
        alive = self.nodes - self.faults
        # graftview: a leader-cascade must leave a quorum of live voters
        # behind (stake is uniform here: quorum = 2n/3+1 over the FULL
        # committee, the node's own formula) — a drill that kills the
        # quorum is a permanent stall, not a view-change storm.
        from ..chaos.plan import LEADER_CASCADE, cascade_k

        cascades = [cascade_k(e.params) for e in self.fault_plan.events
                    if e.target == LEADER_CASCADE]
        quorum = 2 * self.nodes // 3 + 1
        if cascades and alive - sum(cascades) < quorum:
            raise BenchError(
                f"leader-cascade kills {sum(cascades)} leader(s) but "
                f"only {alive - quorum} of the {alive} booted replicas "
                f"are expendable (quorum {quorum} of {self.nodes}); "
                "reduce k or grow the committee")
        # Window headroom: the strict recovery assertion (logs.py) needs
        # commits AFTER every event, and recovery from a kill legitimately
        # costs view changes plus the node-side breaker's failure window —
        # an event too close to teardown would either silently never fire
        # (runner.stop() skips it) or fail a healthy run.  Reject the plan
        # up front instead.  A cascade's recovery is k BACKED-OFF view
        # changes, so its grace follows the pacemaker schedule the run
        # will actually execute (node-parameter overrides win).
        grace = 2 * self.node_parameters.timeout_delay / 1000 + 3
        if cascades:
            cons = self.node_parameters.json.get("consensus", {})
            factor = cons.get("timeout_backoff_factor_pct", 200) / 100.0
            cap = cons.get("timeout_backoff_cap", 60_000) / 1000.0
            jitter = cons.get("timeout_jitter_pct", 10) / 100.0
            base = self.node_parameters.timeout_delay / 1000.0
            # Worst case includes the full jitter draw on every backed-off
            # delay — the core adds up to jitter_pct on top of the
            # schedule, and an unlucky run must not outrun the headroom
            # this check promised it.
            worst = sum(min(max(cap, base), base * factor ** d)
                        for d in range(max(cascades) + 1)) * (1 + jitter)
            grace = max(grace, worst + 3)
        if self.fault_plan.max_time() > self.duration - grace:
            raise BenchError(
                f"fault plan's last event (t={self.fault_plan.max_time():g}s) "
                f"leaves less than {grace:g}s of run-window headroom "
                f"(duration {self.duration}s) for recovery to be "
                "observable; extend --duration or move the event earlier")
        bad = [i for i in self.fault_plan.node_indices() if i >= alive]
        if bad:
            raise BenchError(
                f"fault plan targets node(s) {bad} but only {alive} "
                "replicas will be booted (crash faults are never booted)")
        from ..chaos.plan import client_index

        bad_clients = sorted({
            client_index(e.target) for e in self.fault_plan.events
            if client_index(e.target) is not None
            and client_index(e.target) >= alive})
        if bad_clients:
            raise BenchError(
                f"fault plan surges client(s) {bad_clients} but only "
                f"{alive} clients will be booted (one per alive replica)")
        from ..chaos.plan import sidecar_index

        if any(e.target == "sidecar"
               or sidecar_index(e.target) is not None
               for e in self.fault_plan.events) and not self.tpu_sidecar:
            raise BenchError(
                "fault plan targets the sidecar but this run boots none "
                "(pass --tpu-sidecar / --sidecar-host-crypto)")
        # graftfleet: an indexed sidecar:<i> target must name a fleet
        # member that will actually be booted.
        booted = max(1, self.sidecar_fleet) if self.tpu_sidecar else 0
        bad_sidecars = [i for i in self.fault_plan.sidecar_indices()
                        if i >= booted]
        if bad_sidecars:
            raise BenchError(
                f"fault plan targets sidecar(s) {bad_sidecars} but only "
                f"{booted} sidecar(s) will be booted (raise "
                "sidecar_fleet)")
        missing = [name for name in self.fault_plan.link_names()
                   if self.wan is None or self.wan.by_name(name) is None]
        if missing:
            raise BenchError(
                f"fault plan faults link(s) {missing} the WAN spec does "
                "not name (pass --wan with matching links)")

    def _check_wan(self):
        """Reject WAN links no local proxy can realize, BEFORE boot.
        Locally shapeable: dst 'sidecar' (proxy in front of the verify
        sidecar) and dst 'node:<i>' for an alive replica (proxy in
        front of its client-facing front port).  Inter-replica consensus
        links need real egress shaping — run them on a fleet, where the
        same spec compiles to tc netem."""
        if self.wan is None:
            return
        from ..chaos.plan import node_index

        alive = self.nodes - self.faults
        sidecar_links = [l for l in self.wan.links if l.dst == "sidecar"]
        if len(sidecar_links) > 1:
            # One shared proxy port fronts the sidecar locally; a
            # second link would EADDRINUSE mid-boot.  Per-src sidecar
            # shaping needs per-host egress — the remote harness.
            raise BenchError(
                f"WAN spec names {len(sidecar_links)} sidecar links "
                "but a local run realizes at most one (a single proxy "
                "fronts the shared sidecar; per-src sidecar shaping "
                "needs the remote harness)")
        for link in self.wan.links:
            if link.dst == "sidecar":
                if node_index(link.src) is not None:
                    Print.warn(
                        f"WAN link {link.label()!r}: locally the "
                        "sidecar proxy sits in front of the SHARED "
                        "service, so this shapes every replica's "
                        f"verify path, not just {link.src}'s (per-src "
                        "asymmetry needs the remote harness)")
                continue
            i = node_index(link.dst)
            if i is not None and i < alive:
                # The local proxy fronts the node's CLIENT-facing port:
                # only the client->front hop is actually shaped.  A
                # node/sidecar src would silently measure a different
                # topology than the spec declares.
                if link.src not in ("client", "*"):
                    raise BenchError(
                        f"WAN link {link.label()!r}: src {link.src!r} "
                        "is not locally shapeable (the local proxy "
                        "fronts node fronts, so only client->node:<i> "
                        "links are realizable; inter-replica links "
                        "need the remote harness)")
                continue
            raise BenchError(
                f"WAN link {link.label()!r}: dst {link.dst!r} is not "
                "locally shapeable (local runs proxy the sidecar link "
                "and client->node:<i> fronts; use the remote harness "
                "for inter-replica tc shaping)")

    def _start_wan(self, committee, alive):
        """Boot one WanProxy per realizable link; returns the client
        target addresses with shaped fronts swapped for their proxies.
        The sidecar proxy binds its fixed port (the parameters file
        already points nodes at it)."""
        addresses = list(committee.front_addresses()[:alive])
        if self.wan is None:
            return addresses
        from ..chaos import WanProxy
        from ..chaos.plan import node_index

        for link in self.wan.links:
            if link.dst == "sidecar":
                proxy = WanProxy(("127.0.0.1", self.SIDECAR_PORT),
                                 shape=link.shape,
                                 listen_port=self.WAN_SIDECAR_PORT)
            else:
                i = node_index(link.dst)
                host, port = addresses[i].split(":")
                proxy = WanProxy((host, int(port)), shape=link.shape)
            proxy.start()
            self._wan_proxies[link.label()] = proxy
            if link.dst != "sidecar":
                addresses[node_index(link.dst)] = \
                    f"127.0.0.1:{proxy.port}"
        Print.info(f"WAN: {len(self._wan_proxies)} link prox(ies) up")
        return addresses

    def _stop_wan(self):
        proxies, self._wan_proxies = self._wan_proxies, {}
        for proxy in proxies.values():
            proxy.stop()

    def _boot_twin(self):
        """Boot the Twins equivocating replica: replica 0's keypair, its
        own ports/store/log, and the twin committee view (written by
        run() before the honest half that shares it booted) where its
        identity's addresses point at itself."""
        cmd = CommandMaker.run_node(
            PathMaker.key_file(0),
            PathMaker.twin_committee_file(),
            PathMaker.twin_db_path(),
            PathMaker.parameters_file())
        Print.info("Booting Twins replica (equivocating sibling of "
                   "node 0)...")
        self._twin_proc = self._background_run(
            cmd, PathMaker.twin_log_file(0))

    def _start_fault_plan(self, alive: int):
        """Launch the graftchaos runner for this run window (None when no
        plan).  Event times are offsets from the moment clients start
        being paced — the same origin the plan author reasons in."""
        if self.fault_plan is None or not self.fault_plan.events:
            return None
        # Validation already happened at the top of run() — before the
        # bench paid compile/warmup — off the same construction-time
        # inputs this method sees.
        assert alive == self.nodes - self.faults
        from ..chaos import PlanRunner
        from .faults import LocalFaultInjector

        Print.info(f"Executing fault plan "
                   f"({len(self.fault_plan.events)} event(s))...")
        self._injector = LocalFaultInjector(self)
        runner = PlanRunner(self.fault_plan, self._injector)
        runner.start()
        return runner

    def _finish_fault_plan(self, runner):
        """Stop the runner, un-pause stragglers, and persist the executed
        events next to the logs for the parser's recovery summary.  A
        plan event the window closed on (a stalled injection pushing a
        later event past stop()) is a FAILED chaos run: the acceptance
        criterion is recovery after EVERY event, not every event that
        happened to fire."""
        if runner is None:
            return
        import json

        runner.stop()
        runner.join(timeout=30)
        self._injector.cleanup()
        events = runner.events()
        with open(PathMaker.chaos_events_file(), "w") as f:
            json.dump(events, f)
        if len(events) < len(self.fault_plan.events):
            raise BenchError(
                f"fault plan executed only {len(events)} of "
                f"{len(self.fault_plan.events)} event(s) before the run "
                "window closed (an earlier injection stalled?); the "
                "scripted scenario did not happen as written")

    def run(self, debug=False):
        assert isinstance(debug, bool)
        Print.heading("Starting local benchmark")

        # An unexecutable fault plan or WAN spec must fail HERE, before
        # the bench pays compile + keygen + sidecar warmup for a run
        # that cannot deliver its scripted scenario.
        self._check_fault_plan()
        self._check_wan()

        # Kill any previous testbed and cleanup.
        self._kill_nodes()
        cmd = f"{CommandMaker.cleanup()} ; {CommandMaker.clean_logs()}"
        subprocess.run(["/bin/sh", "-c", cmd], check=True)

        try:
            # Compile the node and create binary aliases.
            Print.info("Compiling the node...")
            subprocess.run(["/bin/sh", "-c", CommandMaker.compile()],
                           check=True, capture_output=True)
            subprocess.run(
                ["/bin/sh", "-c",
                 CommandMaker.alias_binaries(PathMaker.binary_path())],
                check=True)

            # Generate configuration files.
            keys = []
            for i in range(self.nodes):
                filename = PathMaker.key_file(i)
                subprocess.run(
                    ["/bin/sh", "-c", CommandMaker.generate_key(filename)],
                    check=True)
                keys.append(Key.from_file(filename))
            names = [k.name for k in keys]
            self._node_names = names
            bls_pubkeys = None
            if self.scheme == "bls":
                from .config import add_bls_keys

                bls_pubkeys = add_bls_keys(
                    [PathMaker.key_file(i) for i in range(self.nodes)],
                    names)
            committee = LocalCommittee(names, self.BASE_PORT,
                                       bls_pubkeys=bls_pubkeys)
            committee.print(PathMaker.committee_file())
            self.node_parameters.print(PathMaker.parameters_file())

            # Optionally start the TPU verify sidecar first and WAIT until
            # it answers a PING before booting any node. The sidecar only
            # binds its socket after jit warmup, so reachable == ready; a
            # node booted earlier would merely fall back to host verify, but
            # the whole point of this mode is to measure the device path.
            if self.tpu_sidecar:
                self._boot_sidecars(host_crypto=self.sidecar_host_crypto)

            # Do not boot faulty nodes (crash faults, local.py:75-76 in the
            # reference); clients only target alive nodes and split the rate
            # among them.
            alive = self.nodes - self.faults
            # graftwan: proxies come up before any node dials through
            # them; shaped fronts are swapped for their proxy addresses
            # in the clients' target list.
            addresses = self._start_wan(committee, alive)
            rate_share = -(-self.rate // alive)  # ceil
            timeout = self.node_parameters.timeout_delay

            # Twins: the equivocating sibling of node 0 binds its own
            # ports, and the honest committee is SPLIT across the two
            # views — the upper half dials identity 0 at the twin's
            # ports — so both siblings receive votes and either can
            # propose in the shared leader slots.
            twin_view_from = alive if not self.twins else max(1, alive // 2)
            if self.twins:
                from .config import twin_committee, write_committee_json

                write_committee_json(
                    twin_committee(committee, 0, self.TWIN_BASE_PORT),
                    PathMaker.twin_committee_file())

            # Nodes first, then clients with the alive fronts as their
            # --nodes wait list: the client retries those until reachable
            # (its single connect to the target would otherwise race a slow
            # node boot and waste the whole run).
            for i in range(alive):
                cmd = CommandMaker.run_node(
                    PathMaker.key_file(i),
                    PathMaker.committee_file() if i < twin_view_from
                    else PathMaker.twin_committee_file(),
                    PathMaker.db_path(i),
                    PathMaker.parameters_file(),
                    debug=debug)
                self._node_cmds[i] = (cmd, PathMaker.node_log_file(i))
                self._node_procs[i] = self._background_run(
                    cmd, PathMaker.node_log_file(i))
            if self.twins:
                self._boot_twin()

            # graftingress: each node's client optionally fans out over
            # client_shards processes (disjoint user-id and sample-id
            # spaces via the offsets, so shard streams never collide),
            # each signing with per-user keys when verify_ingress is on.
            shards = self.client_shards
            shard_rate = -(-rate_share // shards)  # ceil
            for i, address in enumerate(addresses):
                for j in range(shards):
                    g = i * shards + j  # globally unique shard index
                    cmd = CommandMaker.run_client(
                        address, self.tx_size, shard_rate, timeout,
                        nodes=addresses,
                        sign=self.verify_ingress,
                        forge_pct=(self.forge_pct
                                   if self.verify_ingress else None),
                        seed=(g + 1 if self.verify_ingress or shards > 1
                              else None),
                        user_offset=(g << 24 if self.verify_ingress
                                     else None),
                        sample_offset=(g << 32 if shards > 1 else None))
                    log = PathMaker.client_log_file(i) if shards == 1 \
                        else PathMaker.shard_client_log_file(i, j)
                    self._background_run(cmd, log)
                self._client_targets[i] = (address, self.tx_size,
                                           shard_rate)

            # Wait for all transactions to be processed.
            Print.info(f"Running benchmark ({self.duration} sec)...")
            sleep(2 * timeout / 1000)
            sampler = self._start_metrics_sampler()
            runner = self._start_fault_plan(alive)
            sleep(self.duration)
            self._finish_fault_plan(runner)
            if sampler is not None:
                sampler.stop()
            # Snapshot the scheduler telemetry BEFORE teardown (the
            # OP_STATS counters die with the sidecar process); the parser
            # folds the file into the summary's CONFIG notes.  A sidecar
            # a fault plan killed falls back to the sampler's last
            # in-window snapshot instead of losing the section.
            if self.tpu_sidecar:
                self._fetch_sidecar_stats()
            self._kill_nodes()
            self._stop_wan()

            # Persist the chaos context next to the logs so the parser
            # (and any later re-parse of the directory) judges this run
            # exactly as the bench configured it: the WAN the numbers
            # were shaped under, and the SLO table recovery is held to.
            import json

            if self.wan is not None:
                with open(PathMaker.wan_file(), "w") as f:
                    json.dump(self.wan.to_json(), f)
            if self.fault_plan is not None:
                with open(PathMaker.slo_file(), "w") as f:
                    json.dump(self.slos, f)

            # Parse logs and return the summary.
            Print.info("Parsing logs...")
            parser = LogParser.process(PathMaker.logs_path(),
                                       faults=self.faults)
            if self._degraded:
                # Mark the persisted result: host-mode numbers must never
                # masquerade as device-path data in later aggregation.
                parser.notes.append(
                    "Sidecar mode: host-crypto (DEGRADED - device "
                    "path was unavailable)")
            return parser
        except BenchError:
            # e.g. sidecar readiness failure after the host-crypto retry:
            # sweep everything (incl. a hung sidecar) before propagating.
            self._stop_sampler()
            self._kill_nodes()
            self._stop_wan()
            raise
        except (subprocess.SubprocessError, ParseError) as e:
            self._stop_sampler()
            self._kill_nodes()
            self._stop_wan()
            raise BenchError("Failed to run benchmark", e)

    def _stop_sampler(self):
        sampler = getattr(self, "_sampler", None)
        if sampler is not None:
            sampler.stop()

"""Harness fault injectors: turn graftchaos plan events into process
signals, sidecar RPCs, and link faults against a running bench —
locally (``LocalFaultInjector``) or across an ssh fleet
(``RemoteFaultInjector``).

Separation of concerns: ``hotstuff_tpu/chaos`` owns *what happens when*
(plan model, runner thread, recovery math, link-shape compilation); this
module owns *how* — which pid gets which signal, how a replica reboots
on the same store, how the sidecar's OP_CHAOS hook is reached, and
which host's ``tc`` gets the partition.  The local injector is handed
the LocalBench instance itself, which tracks per-node boot commands,
live processes, and WAN proxies exactly for this purpose; the remote
injector is handed the RemoteRunner transport plus the per-host boot
records the remote Bench keeps.

Design notes:
  * kill is SIGKILL on the whole process group — no clean shutdown, the
    crash-fault model (the restart path must recover from persisted
    state, never from a flushed goodbye).
  * pause/resume is SIGSTOP/SIGCONT on the group: the process keeps its
    sockets but answers nothing — the cheapest faithful proxy for a
    network partition of one replica.  ``cleanup()`` SIGCONTs anything
    still paused so teardown's SIGTERM is actually deliverable.
  * restart re-runs the exact boot command with the log in append mode:
    same keys, same store, same ports — and the pre-fault log survives
    for the parser.
  * sidecar degrade opens a short-lived SidecarClient and posts the
    event's params to the OP_CHAOS hook; a sidecar running without
    ``--chaos`` refuses (reported as an injection failure, because the
    plan demanded a fault the deployment cannot express).
"""

from __future__ import annotations

import json
import os
import signal
import threading

from ..chaos.plan import LEADER_CASCADE, SIDECAR, FaultEvent, cascade_k, \
    client_index, link_name, node_index, sidecar_index


class InjectionError(RuntimeError):
    pass


class LocalFaultInjector:
    def __init__(self, bench):
        self._bench = bench
        self._paused: set[int] = set()
        # graftsurge: live flash-crowd generators ([(proc, timer)]); the
        # timer kills each when its window closes, cleanup() reaps any
        # the run window cut short.
        self._surges: list = []

    def apply(self, event: FaultEvent):
        # graftfleet: the bare "sidecar" target aliases fleet index 0;
        # "sidecar:<i>" picks endpoint i of a --sidecar-fleet run.
        six = 0 if event.target == SIDECAR else sidecar_index(event.target)
        if six is not None:
            fn = getattr(self, f"_sidecar_{event.action}")
            fn(six, **event.params)
            return
        if event.target == LEADER_CASCADE:
            self._cascade_kill(cascade_k(event.params))
            return
        name = link_name(event.target)
        if name is not None:
            getattr(self, f"_link_{event.action}")(name)
            return
        ci = client_index(event.target)
        if ci is not None:
            # ``for`` is a keyword, so surge params route as a dict.
            getattr(self, f"_client_{event.action}")(ci, event.params)
            return
        i = node_index(event.target)
        if i is None:
            raise InjectionError(f"unknown target {event.target!r}")
        getattr(self, f"_node_{event.action}")(i)

    def cleanup(self):
        """SIGCONT any group still paused (teardown's SIGTERM queues
        behind a SIGSTOP forever otherwise), and reap surge generators
        whose window the run outlived."""
        for i in sorted(self._paused):
            try:
                self._signal_node(i, signal.SIGCONT)
            except InjectionError:
                pass
        self._paused.clear()
        surges, self._surges = self._surges, []
        for proc, timer in surges:
            timer.cancel()
            self._kill_surge_proc(proc)

    # -- nodes --------------------------------------------------------------

    def _proc(self, i: int):
        proc = self._bench._node_procs.get(i)
        if proc is None:
            raise InjectionError(f"node {i} was never booted "
                                 "(crash-faulted or out of range)")
        return proc

    def _signal_node(self, i: int, sig):
        proc = self._proc(i)
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError) as e:
            raise InjectionError(f"node {i} signal {sig!r} failed: {e}")

    def _node_kill(self, i: int):
        self._signal_node(i, signal.SIGKILL)
        self._paused.discard(i)
        try:
            self._proc(i).wait(timeout=10)
        except Exception as e:  # noqa: BLE001
            raise InjectionError(f"node {i} did not die on SIGKILL: {e}")

    def _node_restart(self, i: int):
        cmd, log = self._bench._node_cmds[i]
        self._bench._node_procs[i] = self._bench._background_run(
            cmd, log, append=True)

    def _node_pause(self, i: int):
        self._signal_node(i, signal.SIGSTOP)
        self._paused.add(i)

    def _node_resume(self, i: int):
        self._signal_node(i, signal.SIGCONT)
        self._paused.discard(i)

    # -- graftview leader cascade -------------------------------------------

    # How much log tail the round estimate scans per node.  The highest
    # round is always near the END of an append-only log, and this runs
    # on the INJECTION path: reading a multi-GB log in full would delay
    # the SIGKILLs past the event's recorded wall stamp and skew the
    # recovery measurement the drill exists to take.
    _ROUND_SCAN_TAIL_BYTES = 64 * 1024

    def _estimate_round(self) -> int:
        """Best estimate of the round the committee is working on, from
        the highest proposed/committed block round in the node logs (the
        frozen log grammar's ``Created B<r>`` / ``Committed B<r>``
        lines), scanning only each log's tail.  Proposals run ahead of
        commits, so +1 on the max is a round the committee has NOT
        finished yet."""
        import os
        import re

        from .utils import PathMaker

        best = 0
        for i in self._bench._node_procs:
            try:
                with open(PathMaker.node_log_file(i), "rb") as f:
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - self._ROUND_SCAN_TAIL_BYTES))
                    tail = f.read().decode("utf-8", errors="replace")
                for m in re.finditer(r"(?:Created|Committed) B(\d+)\b",
                                     tail):
                    best = max(best, int(m.group(1)))
            except OSError:
                continue
        return best + 1

    def _cascade_kill(self, k: int):
        """graftview drill: SIGKILL the leader of each of the next ``k``
        rounds.  Leader election is round-robin over the SORTED
        committee keys (native LeaderElector), and sorted order means
        the base64-decoded public-key bytes — the same ordering
        std::map<PublicKey, ...> iterates.  Round-robin guarantees the
        chosen nodes each lead within the next committee-size rounds,
        so even a stale round estimate still produces k dead leader
        slots (= k forced view changes); killing them all at once is
        what makes the cascade chain instead of interleaving with
        healthy rounds."""
        import base64

        names = getattr(self._bench, "_node_names", None)
        if not names:
            raise InjectionError(
                "bench records no committee names; leader-cascade needs "
                "a LocalBench run (boot order -> leader slots)")
        order = sorted(range(len(names)),
                       key=lambda i: base64.b64decode(names[i]))
        base = self._estimate_round()
        killed, dead = [], []
        for r in range(base + 1, base + 1 + int(k)):
            i = order[r % len(names)]
            if i in killed:
                continue  # k > committee wraps onto an already-dead slot
            proc = self._bench._node_procs.get(i)
            if proc is None or proc.poll() is not None:
                dead.append(i)  # crash fault / earlier event: already out
                continue
            self._signal_node(i, signal.SIGKILL)
            self._paused.discard(i)
            killed.append(i)
        if not killed:
            raise InjectionError(
                f"leader-cascade kill {k}: no live leader among rounds "
                f"{base + 1}..{base + k} (already dead: {dead})")
        from .utils import Print

        Print.info(f"Leader cascade: killed node(s) {killed} (leaders of "
                   f"rounds {base + 1}..{base + k})")

    # -- sidecar ------------------------------------------------------------

    def _sidecar_proc_of(self, ix: int):
        """Fleet-aware lookup: the per-index dict when the bench keeps
        one, else the legacy single-sidecar attribute for index 0."""
        procs = getattr(self._bench, "_sidecar_procs", None)
        if procs is not None and ix in procs:
            return procs[ix]
        if ix == 0:
            return getattr(self._bench, "_sidecar_proc", None)
        return None

    def _sidecar_kill(self, ix: int = 0):
        proc = self._sidecar_proc_of(ix)
        if proc is None:
            raise InjectionError(f"no sidecar process {ix} to kill")
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
        except (ProcessLookupError, PermissionError) as e:
            raise InjectionError(f"sidecar {ix} SIGKILL failed: {e}")

    def _sidecar_restart(self, ix: int = 0):
        cmds = getattr(self._bench, "_sidecar_cmds", None)
        if cmds is not None and ix in cmds:
            cmd, log = cmds[ix]
            proc = self._bench._background_run(cmd, log, append=True)
            self._bench._sidecar_procs[ix] = proc
            if ix == 0:
                self._bench._sidecar_proc = proc
        else:
            cmd, log = self._bench._sidecar_cmd
            self._bench._sidecar_proc = self._bench._background_run(
                cmd, log, append=True)
        # No readiness wait here: the node-side circuit breaker re-attaches
        # on its next probe once the socket binds, and blocking the runner
        # thread would delay every later plan event by a warmup.

    def _sidecar_degrade(self, ix: int = 0, **params):
        from ..sidecar.client import SidecarClient

        try:
            with SidecarClient(port=self._bench.SIDECAR_PORT + ix,
                               timeout=10.0) as client:
                applied = client.chaos(**params)
        except (OSError, ConnectionError) as e:
            raise InjectionError(f"sidecar {ix} chaos RPC failed: {e}")
        if not applied:
            raise InjectionError(
                "sidecar is running without --chaos; the plan's degrade "
                "event cannot be expressed")

    def _sidecar_wedge(self, ix: int = 0, n: int = 1):
        """graftguard drill: the next ``n`` device launches hang past
        their guard deadline (ChaosState's ``wedge`` knob over the same
        OP_CHAOS RPC as degrade) — the in-sidecar supervisor must answer
        the wedged batch from the host path, quarantine it, and
        crash-only-reboot the engine; same --chaos refusal contract."""
        self._sidecar_degrade(ix, wedge=int(n))

    # -- graftsurge client surges -------------------------------------------

    @staticmethod
    def _kill_surge_proc(proc):
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def _client_surge(self, i: int, params: dict):
        """Flash crowd against replica i: boot an EXTRA load generator
        at (x-1)x the baseline client's rate for ``for`` seconds, then
        kill it.  The surge client logs to surge-client-<i>.log —
        outside the parser's client glob, so offered surge load never
        counts as benchmark input rate (goodput under surge is judged
        from the commit/metrics timelines instead)."""
        from .commands import CommandMaker
        from .utils import PathMaker

        targets = getattr(self._bench, "_client_targets", {})
        info = targets.get(i)
        if info is None:
            raise InjectionError(
                f"client {i} was never booted (crash-faulted replica or "
                "out of range); the surge has no baseline to multiply")
        address, tx_size, rate_share = info
        from ..chaos.plan import SURGE_DEFAULT_X, surge_window_s

        x = float(params.get("x", SURGE_DEFAULT_X))
        duration = surge_window_s(params)
        extra_rate = max(1, int(round((x - 1) * rate_share)))
        # Heavy-tailed by default: a flash crowd IS bursty arrivals, so
        # the surge generator simulates users rather than a constant
        # stream (seeded off the replica index for reproducible runs).
        cmd = CommandMaker.run_client(
            address, tx_size, extra_rate, 0,
            users=max(2, extra_rate // 10), seed=1000 + i)
        proc = self._bench._background_run(
            cmd, PathMaker.surge_client_log_file(i), append=True)

        def _end():
            # Late-bound closure: `timer` is assigned below, before
            # start() can fire this.
            self._kill_surge_proc(proc)
            try:
                self._surges.remove((proc, timer))
            except ValueError:
                pass  # cleanup() already reaped it

        timer = threading.Timer(duration, _end)
        timer.daemon = True
        self._surges.append((proc, timer))
        timer.start()

    # -- graftwan links -----------------------------------------------------

    def _proxy(self, name: str):
        proxy = getattr(self._bench, "_wan_proxies", {}).get(name)
        if proxy is None:
            raise InjectionError(
                f"no WAN proxy realizes link {name!r} on this run "
                "(pass --wan with a spec naming it)")
        return proxy

    def _link_partition(self, name: str):
        self._proxy(name).partition()

    def _link_heal(self, name: str):
        self._proxy(name).heal()


class RemoteFaultInjector:
    """Executes fault plans across an ssh fleet (harness/remote.Bench).

    Same plan schema as the local injector; the mechanisms change:

      * node kill/pause/resume are ``pkill`` signals against the node
        pattern on that replica's host (one node per host, the remote
        bench's layout) — ``pkill`` exiting non-zero means no process
        matched, which is an injection failure, not a transport one;
      * node restart re-runs the recorded boot command via the
        background wrapper in APPEND mode, so the pre-fault log
        survives for the parser (the same same-store contract as the
        local injector);
      * link partition/heal compile to ``tc qdisc change`` on every
        host whose egress carries the link (chaos/netem.py owns the
        band numbering; the commands target the qdiscs ``Bench``
        installed from the same spec);
      * sidecar degrade reaches OP_CHAOS through a python one-liner on
        the sidecar host's checkout (the RPC must originate next to the
        sidecar: its port is not assumed reachable from the
        orchestrator); kill/restart pkill + reboot it there.  All three
        need a configured sidecar host — a plan demanding a fault the
        deployment cannot express fails the injection, same contract as
        a --chaos-less local sidecar.

    Event wall stamps are taken by the PlanRunner on the orchestrator's
    clock, while recovery comes from commit stamps in REMOTE logs —
    per-fault recovery latency on a fleet therefore carries the fleet's
    clock skew, exactly like the reference's measurement pipeline (its
    client/node stamps span hosts too).  NTP-synced fleets keep this in
    the low milliseconds.
    """

    # Bracketed dot: the ssh wrapper shell's own cmdline contains this
    # pattern verbatim, and a regex that matches its own text makes
    # ``pkill -f`` signal the wrapper too (a -KILL turns into rc=137 on
    # a successful injection; a -STOP parks the ssh session until the
    # transport timeout).  ``[.]`` matches the node's literal dot but
    # not the bracketed pattern text itself.
    NODE_PATTERN = r"[.]/node run"
    SIDECAR_PATTERN = r"hotstuff_tpu[.]sidecar"

    # Injections are milliseconds of remote work (pkill, tc change, one
    # RPC); never let one share the transport's install-sized default
    # bound — a wedged host must fail the EVENT fast, not stall the
    # PlanRunner past the run window.
    INJECT_TIMEOUT_S = 60.0

    def __init__(self, runner, hosts, repo, node_boots, wan=None,
                 peers=None, dev="eth0", sidecar_host=None,
                 sidecar_port=7100, sidecar_boot=None):
        self._runner = runner
        self._hosts = list(hosts)
        self._repo = repo
        # {i: (command, log_file)} recorded by Bench._run_single.
        self._node_boots = dict(node_boots)
        self._wan = wan
        self._peers = dict(peers or {})
        self._dev = dev
        self._sidecar_host = sidecar_host
        self._sidecar_port = sidecar_port
        self._sidecar_boot = sidecar_boot
        self._paused: set[int] = set()

    def apply(self, event: FaultEvent):
        if event.target == SIDECAR:
            getattr(self, f"_sidecar_{event.action}")(**event.params)
            return
        if sidecar_index(event.target) is not None:
            # graftfleet is local-harness only for now: the remote bench
            # records one sidecar host/boot, so indexed targets cannot
            # be expressed against a fleet it never booted.
            raise InjectionError(
                "sidecar:<i> fleet targets are local-harness only (the "
                "remote bench tracks a single sidecar host)")
        if event.target == LEADER_CASCADE:
            # Pre-flight (remote._check_fault_plan) rejects cascade plans
            # before boot; this is the belt for hand-driven injectors
            # (the remote bench has no live round estimate to pick
            # leaders from).
            raise InjectionError(
                "leader-cascade events are local-harness only (the "
                "remote bench cannot estimate the live round)")
        name = link_name(event.target)
        if name is not None:
            getattr(self, f"_link_{event.action}")(name)
            return
        if client_index(event.target) is not None:
            # Pre-flight (remote._check_fault_plan) rejects surge plans
            # before boot; this is the belt for hand-driven injectors.
            raise InjectionError(
                "client surge events are local-harness only (the remote "
                "bench tracks no client boot commands)")
        i = node_index(event.target)
        if i is None:
            raise InjectionError(f"unknown target {event.target!r}")
        getattr(self, f"_node_{event.action}")(i)

    def cleanup(self):
        """SIGCONT any host still paused (mirrors the local injector:
        teardown's pkill queues behind a SIGSTOP forever otherwise)."""
        for i in sorted(self._paused):
            try:
                self._pkill(i, "CONT")
            except InjectionError:
                pass
        self._paused.clear()

    # -- nodes --------------------------------------------------------------

    def _host(self, i: int) -> str:
        if not 0 <= i < len(self._hosts):
            raise InjectionError(f"node {i} has no host (fleet of "
                                 f"{len(self._hosts)})")
        return self._hosts[i]

    def _run(self, host, command, what):
        from .remote import ExecutionError

        try:
            self._runner.run(host, command,
                             timeout=self.INJECT_TIMEOUT_S)
        except ExecutionError as e:
            raise InjectionError(f"{what} failed on {host}: {e}")

    def _pkill(self, i: int, sig: str, pattern=None):
        self._run(self._host(i),
                  f"pkill -{sig} -f '{pattern or self.NODE_PATTERN}'",
                  f"node {i} pkill -{sig}")

    def _node_kill(self, i: int):
        self._pkill(i, "KILL")
        self._paused.discard(i)

    def _node_restart(self, i: int):
        from .remote import ExecutionError

        boot = self._node_boots.get(i)
        if boot is None:
            raise InjectionError(f"node {i} has no recorded boot command")
        cmd, log = boot
        try:
            self._runner.run_background(self._host(i), cmd, log,
                                        append=True,
                                        timeout=self.INJECT_TIMEOUT_S)
        except ExecutionError as e:
            raise InjectionError(f"node {i} restart failed: {e}")

    def _node_pause(self, i: int):
        self._pkill(i, "STOP")
        self._paused.add(i)

    def _node_resume(self, i: int):
        self._pkill(i, "CONT")
        self._paused.discard(i)

    # -- graftwan links -----------------------------------------------------

    def _link_tc(self, name: str, compile_fn, what: str):
        from ..chaos.netem import WanError

        if self._wan is None:
            raise InjectionError(
                f"plan faults link {name!r} but this run shapes no WAN "
                "(pass --wan)")
        if self._wan.by_name(name) is None:
            raise InjectionError(f"WAN spec names no link {name!r}")
        ran = 0
        for i, host in enumerate(self._hosts):
            try:
                cmds = compile_fn(self._wan, name, f"node:{i}",
                                  self._peers, self._dev)
            except WanError as e:
                raise InjectionError(f"link {name!r}: {e}")
            for cmd in cmds:
                self._run(host, cmd, f"link {name!r} {what}")
                ran += 1
        if not ran:
            raise InjectionError(
                f"link {name!r} touches no egress on this fleet "
                "(src/dst outside the booted hosts)")

    def _link_partition(self, name: str):
        from ..chaos.netem import tc_partition_commands

        self._link_tc(name, tc_partition_commands, "partition")

    def _link_heal(self, name: str):
        from ..chaos.netem import tc_heal_commands

        self._link_tc(name, tc_heal_commands, "heal")

    # -- sidecar ------------------------------------------------------------

    def _sidecar_host_or_fail(self) -> str:
        if not self._sidecar_host:
            raise InjectionError(
                "plan targets the sidecar but this fleet runs none "
                "(configure a sidecar host)")
        return self._sidecar_host

    def _sidecar_kill(self):
        host = self._sidecar_host_or_fail()
        self._run(host, f"pkill -KILL -f '{self.SIDECAR_PATTERN}'",
                  "sidecar pkill -KILL")

    def _sidecar_restart(self):
        from .remote import ExecutionError

        host = self._sidecar_host_or_fail()
        if self._sidecar_boot is None:
            raise InjectionError("sidecar has no recorded boot command")
        cmd, log = self._sidecar_boot
        try:
            self._runner.run_background(host, cmd, log, append=True,
                                        timeout=self.INJECT_TIMEOUT_S)
        except ExecutionError as e:
            raise InjectionError(f"sidecar restart failed: {e}")

    def _sidecar_degrade(self, **params):
        import shlex

        host = self._sidecar_host_or_fail()
        snippet = (
            "import json, sys; "
            "from hotstuff_tpu.sidecar.client import SidecarClient; "
            f"c = SidecarClient(port={self._sidecar_port}, timeout=10.0); "
            "ok = c.chaos(**json.loads(sys.argv[1])); c.close(); "
            "sys.exit(0 if ok else 3)")
        cmd = (f"cd {self._repo} && python3 -c {shlex.quote(snippet)} "
               f"{shlex.quote(json.dumps(params))}")
        self._run(host, cmd, "sidecar chaos RPC")

    def _sidecar_wedge(self, n: int = 1):
        """graftguard drill over the fleet: same OP_CHAOS RPC as
        degrade, with the wedge knob (see LocalFaultInjector)."""
        self._sidecar_degrade(wedge=int(n))

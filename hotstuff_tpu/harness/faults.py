"""Harness fault injector: turns graftchaos plan events into process
signals and sidecar RPCs against a running LocalBench.

Separation of concerns: ``hotstuff_tpu/chaos`` owns *what happens when*
(plan model, runner thread, recovery math); this module owns *how* —
which pid gets which signal, how a replica reboots on the same store,
and how the sidecar's OP_CHAOS hook is reached.  The injector is handed
the LocalBench instance itself, which tracks per-node boot commands and
live processes exactly for this purpose.

Design notes:
  * kill is SIGKILL on the whole process group — no clean shutdown, the
    crash-fault model (the restart path must recover from persisted
    state, never from a flushed goodbye).
  * pause/resume is SIGSTOP/SIGCONT on the group: the process keeps its
    sockets but answers nothing — the cheapest faithful proxy for a
    network partition of one replica.  ``cleanup()`` SIGCONTs anything
    still paused so teardown's SIGTERM is actually deliverable.
  * restart re-runs the exact boot command with the log in append mode:
    same keys, same store, same ports — and the pre-fault log survives
    for the parser.
  * sidecar degrade opens a short-lived SidecarClient and posts the
    event's params to the OP_CHAOS hook; a sidecar running without
    ``--chaos`` refuses (reported as an injection failure, because the
    plan demanded a fault the deployment cannot express).
"""

from __future__ import annotations

import os
import signal

from ..chaos.plan import SIDECAR, FaultEvent, node_index


class InjectionError(RuntimeError):
    pass


class LocalFaultInjector:
    def __init__(self, bench):
        self._bench = bench
        self._paused: set[int] = set()

    def apply(self, event: FaultEvent):
        if event.target == SIDECAR:
            fn = getattr(self, f"_sidecar_{event.action}")
            fn(**event.params)
            return
        i = node_index(event.target)
        if i is None:
            raise InjectionError(f"unknown target {event.target!r}")
        getattr(self, f"_node_{event.action}")(i)

    def cleanup(self):
        """SIGCONT any group still paused (teardown's SIGTERM queues
        behind a SIGSTOP forever otherwise)."""
        for i in sorted(self._paused):
            try:
                self._signal_node(i, signal.SIGCONT)
            except InjectionError:
                pass
        self._paused.clear()

    # -- nodes --------------------------------------------------------------

    def _proc(self, i: int):
        proc = self._bench._node_procs.get(i)
        if proc is None:
            raise InjectionError(f"node {i} was never booted "
                                 "(crash-faulted or out of range)")
        return proc

    def _signal_node(self, i: int, sig):
        proc = self._proc(i)
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError) as e:
            raise InjectionError(f"node {i} signal {sig!r} failed: {e}")

    def _node_kill(self, i: int):
        self._signal_node(i, signal.SIGKILL)
        self._paused.discard(i)
        try:
            self._proc(i).wait(timeout=10)
        except Exception as e:  # noqa: BLE001
            raise InjectionError(f"node {i} did not die on SIGKILL: {e}")

    def _node_restart(self, i: int):
        cmd, log = self._bench._node_cmds[i]
        self._bench._node_procs[i] = self._bench._background_run(
            cmd, log, append=True)

    def _node_pause(self, i: int):
        self._signal_node(i, signal.SIGSTOP)
        self._paused.add(i)

    def _node_resume(self, i: int):
        self._signal_node(i, signal.SIGCONT)
        self._paused.discard(i)

    # -- sidecar ------------------------------------------------------------

    def _sidecar_kill(self):
        proc = self._bench._sidecar_proc
        if proc is None:
            raise InjectionError("no sidecar process to kill")
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
        except (ProcessLookupError, PermissionError) as e:
            raise InjectionError(f"sidecar SIGKILL failed: {e}")

    def _sidecar_restart(self):
        cmd, log = self._bench._sidecar_cmd
        self._bench._sidecar_proc = self._bench._background_run(
            cmd, log, append=True)
        # No readiness wait here: the node-side circuit breaker re-attaches
        # on its next probe once the socket binds, and blocking the runner
        # thread would delay every later plan event by a warmup.

    def _sidecar_degrade(self, **params):
        from ..sidecar.client import SidecarClient

        try:
            with SidecarClient(port=self._bench.SIDECAR_PORT,
                               timeout=10.0) as client:
                applied = client.chaos(**params)
        except (OSError, ConnectionError) as e:
            raise InjectionError(f"sidecar chaos RPC failed: {e}")
        if not applied:
            raise InjectionError(
                "sidecar is running without --chaos; the plan's degrade "
                "event cannot be expressed")

"""Harness utilities: file-layout conventions, colored printing, progress.

Capability mirror of the reference's benchmark/benchmark/utils.py:12-134
(PathMaker / Print / progress_bar), with the same on-disk naming scheme so
results remain comparable across harnesses.
"""

from __future__ import annotations

import sys
from os.path import join


class BenchError(Exception):
    def __init__(self, message, error=None):
        super().__init__(message)
        self.message = message
        self.cause = error


class PathMaker:
    @staticmethod
    def binary_path():
        return join("native", "build")

    @staticmethod
    def node_crate_path():
        return "native"

    @staticmethod
    def committee_file():
        return ".committee.json"

    @staticmethod
    def parameters_file():
        return ".parameters.json"

    @staticmethod
    def key_file(i):
        assert isinstance(i, int) and i >= 0
        return f".node-{i}.json"

    @staticmethod
    def db_path(i):
        assert isinstance(i, int) and i >= 0
        return f".db-{i}"

    @staticmethod
    def logs_path():
        return "logs"

    @staticmethod
    def node_log_file(i):
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"node-{i}.log")

    @staticmethod
    def client_log_file(i):
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"client-{i}.log")

    @staticmethod
    def shard_client_log_file(i, j):
        """graftingress client shard j of node i.  INSIDE the
        client-*.log glob on purpose: shards are the baseline load,
        split across processes, and each must parse as a benchmark
        client (per-shard fairness rides on the per-log accounting)."""
        assert isinstance(i, int) and i >= 0
        assert isinstance(j, int) and j >= 0
        return join(PathMaker.logs_path(), f"client-{i}-{j}.log")

    @staticmethod
    def surge_client_log_file(i):
        """graftsurge flash-crowd generator aimed at replica i.  OUTSIDE
        the client-*.log glob on purpose: surge load is offered on top
        of the baseline, and its (killed) generator must not parse as a
        failed benchmark client or inflate the input rate."""
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"surge-client-{i}.log")

    @staticmethod
    def sidecar_log_file(i=None):
        """graftfleet: sidecar i of a fleet logs to sidecar-<i>.log; the
        single-sidecar run keeps the legacy un-indexed name so existing
        tooling and result diffs stay comparable."""
        if i is None:
            return join(PathMaker.logs_path(), "sidecar.log")
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"sidecar-{i}.log")

    @staticmethod
    def sidecar_stats_file(i=None):
        """verifysched OP_STATS snapshot, fetched at teardown (JSON);
        per-endpoint sidecar-stats-<i>.json under graftfleet."""
        if i is None:
            return join(PathMaker.logs_path(), "sidecar-stats.json")
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"sidecar-stats-{i}.json")

    @staticmethod
    def sidecar_spans_file():
        """grafttrace sidecar span JSONL (obs/spans.py schema), written
        live by the sidecar behind --trace; obs/trace.py merges it into
        the run's trace.json."""
        return join(PathMaker.logs_path(), "sidecar-spans.jsonl")

    @staticmethod
    def metrics_file():
        """Live OP_STATS time series (obs/sampler.py JSONL), appended
        at a fixed interval DURING the run window."""
        return join(PathMaker.logs_path(), "metrics.jsonl")

    @staticmethod
    def trace_file():
        """Chrome-trace-event / Perfetto-loadable artifact built from
        the run's merged spans (obs/trace.write_run_trace)."""
        return join(PathMaker.logs_path(), "trace.json")

    @staticmethod
    def clock_offsets_file():
        """Per-log-file clock offsets in seconds (obs/trace.py), probed
        over the ssh transport on remote runs; absent locally."""
        return join(PathMaker.logs_path(), "clock-offsets.json")

    @staticmethod
    def chaos_events_file():
        """graftchaos executed-event record (JSON list, PlanRunner.events
        shape); written after the run window, read back by LogParser for
        the per-fault recovery-latency summary."""
        return join(PathMaker.logs_path(), "chaos-events.json")

    @staticmethod
    def wan_file():
        """graftwan spec snapshot (chaos/netem.WanSpec.to_json); written
        by the harness when a run shapes links so the parser can note
        what WAN the numbers were measured under."""
        return join(PathMaker.logs_path(), "wan.json")

    @staticmethod
    def slo_file():
        """Per-fault-class recovery SLO table (chaos/slo schema) the
        parser judges chaos events against; absent = defaults."""
        return join(PathMaker.logs_path(), "slo.json")

    @staticmethod
    def twin_log_file(i):
        """Log of a Twins equivocating replica — named OUTSIDE the
        node-*.log glob so twin commits never pollute the committee
        metrics (they only feed the safety assertion)."""
        assert isinstance(i, int) and i >= 0
        return join(PathMaker.logs_path(), f"twin-{i}.log")

    @staticmethod
    def twin_committee_file():
        """Committee view booted into a Twins replica: identical address
        book except the twin's own entry binds fresh ports."""
        return ".committee-twin.json"

    @staticmethod
    def twin_db_path():
        return ".db-twin"

    @staticmethod
    def results_path():
        return "results"

    @staticmethod
    def result_file(faults, nodes, rate, tx_size, chain=2):
        tag = "" if chain == 2 else f"{chain}chain-"
        return join(
            PathMaker.results_path(),
            f"bench-{tag}{faults}-{nodes}-{rate}-{tx_size}.txt",
        )

    @staticmethod
    def plot_path():
        return "plots"

    @staticmethod
    def agg_file(type, faults, nodes, rate, tx_size, max_latency=None):
        name = f"{type}-{faults}-{nodes}-{rate}-{tx_size}"
        if max_latency is not None:
            name += f"-{max_latency}"
        return join(PathMaker.plot_path(), f"{name}.txt")

    @staticmethod
    def plot_file(name, ext):
        return join(PathMaker.plot_path(), f"{name}.{ext}")


class Color:
    HEADER = "\033[95m"
    OK_BLUE = "\033[94m"
    OK_GREEN = "\033[92m"
    WARNING = "\033[93m"
    FAIL = "\033[91m"
    END = "\033[0m"
    BOLD = "\033[1m"


class Print:
    @staticmethod
    def heading(message):
        assert isinstance(message, str)
        print(f"{Color.OK_GREEN}{message}{Color.END}")

    @staticmethod
    def info(message):
        assert isinstance(message, str)
        print(message)

    @staticmethod
    def warn(message):
        assert isinstance(message, str)
        print(f"{Color.BOLD}{Color.WARNING}WARN{Color.END}: {message}")

    @staticmethod
    def error(e):
        assert isinstance(e, BenchError)
        print(f"\n{Color.BOLD}{Color.FAIL}ERROR{Color.END}: {e}\n")
        if e.cause is not None:
            print(f"Caused by: \n{e.cause}\n")


def progress_bar(it, prefix="", size=30, file=sys.stdout):
    count = len(it)

    def show(j):
        x = int(size * j / max(count, 1))
        file.write(f"{prefix}[{'#' * x}{'.' * (size - x)}] {j}/{count}\r")
        file.flush()

    show(0)
    for i, item in enumerate(it):
        yield item
        show(i + 1)
    file.write("\n")
    file.flush()

"""graftsurge load model: the Python twin of the C++ client's
multi-user open-loop generator (native/src/node/rate_pacer.hpp
``UserLoadModel``).

The C++ model drives live benches; this one drives everything that
cannot boot a committee — the bench ``surge`` headline probe, the
scheduler overload tests, and any harness experiment that needs a
seeded heavy-tailed arrival stream on a virtual clock.  The two share
one model (not one implementation): N users, each with mean-1
heavy-tailed inter-arrival multipliers (lognormal ``exp(sigma Z -
sigma^2/2)`` or Pareto ``xm U^(-1/alpha)``, ``xm = (alpha-1)/alpha``)
on a per-user mean gap of ``users / rate`` seconds, an optional
sinusoidal diurnal profile with mean exactly 1 over its period, and
per-user jittered exponential backoff on BUSY.  Aggregate mean rate ==
``rate`` by construction.

Everything is deterministic in the seed, and all time is
caller-supplied seconds — no wall clock anywhere (the graftlint timing
rules stay quiet because there is nothing to fence)."""

from __future__ import annotations

import heapq
import math
import random

LOGNORMAL = "lognormal"
PARETO = "pareto"


class UserLoad:
    def __init__(self, rate: float, users: int, seed: int = 1,
                 dist: str = LOGNORMAL, sigma: float = 1.5,
                 alpha: float = 2.5, diurnal_amp: float = 0.0,
                 diurnal_period_s: float = 600.0,
                 busy_base_s: float = 0.05):
        if dist not in (LOGNORMAL, PARETO):
            raise ValueError(f"unknown arrival dist {dist!r}")
        if rate <= 0 or users < 1:
            raise ValueError("rate must be > 0 and users >= 1")
        self.rate = float(rate)
        self.users = int(users)
        self.dist = dist
        self.sigma = float(sigma)
        self.alpha = max(1.05, float(alpha))
        self.diurnal_amp = float(diurnal_amp)
        self.diurnal_period_s = float(diurnal_period_s)
        self.busy_base_s = float(busy_base_s)
        self._rng = random.Random(seed)
        self._mean_gap = self.users / self.rate
        # (next_arrival_t, user) min-heap; random start phase keeps the
        # aggregate at its mean rate from t=0.
        self._heap = [(self._rng.uniform(0.0, self._mean_gap), u)
                      for u in range(self.users)]
        heapq.heapify(self._heap)
        self._attempts = [0] * self.users
        self._busy_until = -1.0
        self._busy_hint_s = 0.0
        self.sent = 0
        self.deferred = 0
        self.busy_events = 0

    def profile(self, t: float) -> float:
        """Diurnal rate multiplier at t (mean exactly 1 per period)."""
        if self.diurnal_amp <= 0.0:
            return 1.0
        return 1.0 + self.diurnal_amp * math.sin(
            2.0 * math.pi * t / self.diurnal_period_s)

    def sample_gap(self, t: float) -> float:
        """One inter-arrival gap for a user at time t (test hook; drawn
        from the generator's own rng stream)."""
        if self.dist == PARETO:
            u = max(1e-12, self._rng.random())
            x = (self.alpha - 1.0) / self.alpha * u ** (-1.0 / self.alpha)
        else:
            z = self._rng.gauss(0.0, 1.0)
            x = math.exp(self.sigma * z - 0.5 * self.sigma * self.sigma)
        return max(self._mean_gap * x / self.profile(t), 1e-9)

    def arrivals(self, now: float, out_users: list | None = None) -> int:
        """Transactions due at `now` (monotonic calls).  Arrivals inside
        a busy window defer per-user with jittered exponential backoff —
        deferred, never dropped (this is an open loop).  graftingress:
        ``out_users`` (optional) receives the user index of each due
        arrival in order — the signed-ingress probe derives the per-user
        keypair from it (same contract as the C++ UserLoadModel)."""
        due = 0
        while self._heap and self._heap[0][0] <= now:
            t, user = heapq.heappop(self._heap)
            if t < self._busy_until:
                self._attempts[user] = min(self._attempts[user] + 1, 6)
                base = max(self._busy_hint_s, self.busy_base_s)
                delay = base * (2 ** self._attempts[user]) * \
                    self._rng.uniform(0.5, 1.5)
                heapq.heappush(self._heap,
                               (self._busy_until + delay, user))
                self.deferred += 1
                continue
            self._attempts[user] = 0
            due += 1
            self.sent += 1
            if out_users is not None:
                out_users.append(user)
            heapq.heappush(self._heap, (t + self.sample_gap(t), user))
        return due

    def busy(self, now: float, hint_s: float = 0.0):
        """A BUSY reply observed at `now` with a retry-after hint."""
        self._busy_hint_s = max(0.0, float(hint_s))
        self._busy_until = max(
            self._busy_until,
            now + max(self._busy_hint_s, self.busy_base_s))
        self.busy_events += 1

"""Remote (multi-host) benchmark orchestration over plain ssh/scp.

Capability mirror of benchmark/benchmark/remote.py:31-300 — install,
update, configure, run, and collect logs across a fleet of hosts — built
on subprocess ssh instead of fabric/paramiko (neither ships in this
image). Hosts come from a `hosts` list in settings.json or an explicit
list; cloud instance lifecycle (create/start/stop/terminate) lives in
instance.py and is gated on boto3 availability.

graftwan promotes this from a plain matrix driver to the distributed
chaos matrix: ``Bench`` accepts a fault plan (the same declarative
graftchaos schema the local harness runs) executed mid-run by a
``RemoteFaultInjector`` over the ssh transport, and a WAN spec
(chaos/netem.py) compiled to per-host ``tc netem`` shaping installed
before the run and torn down after.  Executed events persist into the
downloaded logs directory as ``chaos-events.json`` — the same contract
``LogParser.process`` already consumes — so per-fault recovery latency
and SLO verdicts come out of a fleet run exactly as they do locally.

Transport discipline: ssh's ConnectTimeout bounds the *dial*, not a
hung remote command, so every ``run``/``put``/``get`` carries a
subprocess timeout (the graftlint ``unbounded-socket-op`` rule enforces
this for ssh/scp argv the same way it does for raw sockets).
"""

from __future__ import annotations

import json
import shlex
import subprocess
from os.path import join
from time import sleep

from .commands import CommandMaker
from .config import Committee, Key
from .logs import LogParser, ParseError
from .utils import BenchError, PathMaker, Print, progress_bar


class FabricError(Exception):
    """SSH transport failure (name kept for parity with the reference's
    error taxonomy)."""


class ExecutionError(Exception):
    pass


class RemoteRunner:
    """Thin ssh/scp wrapper used by Bench below.

    ``command_timeout``/``copy_timeout`` bound the whole remote
    execution: a wedged remote host (the exact failure class graftchaos
    scripts) must surface as an error in this process, never park an
    orchestrator thread forever.
    """

    # Generous defaults: install/update legitimately run apt + cmake for
    # minutes; a fault-plan pkill takes milliseconds but shares the
    # bound (callers pass a tighter one where it matters).
    COMMAND_TIMEOUT_S = 900.0
    COPY_TIMEOUT_S = 300.0

    def __init__(self, user, key_path, connect_timeout=10):
        self.user = user
        self.key_path = key_path
        self.connect_timeout = connect_timeout

    def _ssh_base(self, host):
        return [
            "ssh", "-i", self.key_path,
            "-o", "StrictHostKeyChecking=no",
            "-o", f"ConnectTimeout={self.connect_timeout}",
            f"{self.user}@{host}",
        ]

    def run(self, host, command, check=True, hide=True, timeout=None):
        try:
            result = subprocess.run(
                self._ssh_base(host) + [command],
                capture_output=hide, text=True,
                timeout=timeout if timeout is not None
                else self.COMMAND_TIMEOUT_S)
        except subprocess.TimeoutExpired as e:
            raise ExecutionError(
                f"[{host}] {command!r} hung past {e.timeout:g}s "
                "(wedged host?)")
        if check and result.returncode != 0:
            raise ExecutionError(
                f"[{host}] {command!r} failed: {result.stderr}")
        return result

    def run_background(self, host, command, log_file, append=False,
                       timeout=None):
        # nohup + setsid so the process survives the ssh session.  The
        # command is shlex-quoted INTO the sh -c argument: boot commands
        # legitimately carry single quotes (pkill patterns, --nodes
        # lists), and naive '{command}' wrapping broke on every one.
        redirect = ">>" if append else ">"
        wrapped = (f"nohup setsid sh -c {shlex.quote(command)} "
                   f"{redirect} {log_file} 2>&1 < /dev/null &")
        return self.run(host, wrapped, timeout=timeout)

    def put(self, host, local, remote, timeout=None):
        try:
            result = subprocess.run(
                ["scp", "-i", self.key_path,
                 "-o", "StrictHostKeyChecking=no",
                 local, f"{self.user}@{host}:{remote}"],
                capture_output=True, text=True,
                timeout=timeout if timeout is not None
                else self.COPY_TIMEOUT_S)
        except subprocess.TimeoutExpired as e:
            raise FabricError(
                f"scp to {host} hung past {e.timeout:g}s")
        if result.returncode != 0:
            raise FabricError(f"scp to {host} failed: {result.stderr}")

    def get(self, host, remote, local, timeout=None):
        try:
            result = subprocess.run(
                ["scp", "-i", self.key_path,
                 "-o", "StrictHostKeyChecking=no",
                 f"{self.user}@{host}:{remote}", local],
                capture_output=True, text=True,
                timeout=timeout if timeout is not None
                else self.COPY_TIMEOUT_S)
        except subprocess.TimeoutExpired as e:
            raise FabricError(
                f"scp from {host} hung past {e.timeout:g}s")
        if result.returncode != 0:
            raise FabricError(f"scp from {host} failed: {result.stderr}")


class Bench:
    """Multi-host benchmark: one node per host, one client per node."""

    # tc shaping applies to each host's primary interface; override via
    # settings.json "wan_dev" when the fleet uses another name.
    WAN_DEV = "eth0"

    def __init__(self, settings, hosts, user="ubuntu", fault_plan=None,
                 wan=None, slos=None):
        self.settings = settings
        self.hosts = hosts
        self.runner = RemoteRunner(user, settings.key_path)
        self.wan_dev = getattr(settings, "wan_dev", None) or self.WAN_DEV
        # graftwan: parse/validate the chaos inputs NOW — a malformed
        # plan must fail before any host is touched, same contract as
        # LocalBench.
        from ..chaos import PlanError, SloError, WanError, parse_plan, \
            parse_slos, parse_wan

        try:
            self.fault_plan = parse_plan(fault_plan) if fault_plan else None
        except PlanError as e:
            raise BenchError("Invalid fault plan", e)
        try:
            self.wan = parse_wan(wan) if wan else None
        except WanError as e:
            raise BenchError("Invalid WAN spec", e)
        try:
            self.slos = parse_slos(slos)
        except SloError as e:
            raise BenchError("Invalid SLO table", e)

    # Provisioning legitimately outlives the runner's 900 s default: a
    # cold apt + full cmake tree build can take tens of minutes, and
    # before the subprocess timeouts landed these calls were unbounded.
    PROVISION_TIMEOUT_S = 3600.0

    def install(self):
        """Install the toolchain + clone the repo on every host
        (remote.py:52-81 analogue, apt/cmake instead of rustup)."""
        cmd = " && ".join([
            "sudo apt-get update",
            "sudo apt-get -y install build-essential cmake ninja-build "
            "python3 python3-pip",
            f"(git clone {self.settings.repo_url} || true)",
        ])
        for host in progress_bar(self.hosts, prefix="Installing:"):
            self.runner.run(host, cmd, timeout=self.PROVISION_TIMEOUT_S)

    def update(self):
        """Pull + rebuild on every host (remote.py:115-130 analogue)."""
        repo = self.settings.repo_name
        cmd = " && ".join([
            f"cd {repo}",
            f"git fetch -f && git checkout -f {self.settings.branch}",
            "git pull -f",
            CommandMaker.compile(),
        ])
        for host in progress_bar(self.hosts, prefix="Updating:"):
            self.runner.run(host, cmd, timeout=self.PROVISION_TIMEOUT_S)

    def _config(self, hosts, node_parameters):
        """Generate keys locally, build the committee from host IPs, upload
        configs (remote.py:132-177 analogue)."""
        subprocess.run(["/bin/sh", "-c", CommandMaker.cleanup()], check=False)
        keys = []
        key_files = [PathMaker.key_file(i) for i in range(len(hosts))]
        for filename in key_files:
            subprocess.run(
                ["/bin/sh", "-c",
                 join(PathMaker.binary_path(), "node")
                 + f" keys --filename {filename}"],
                check=True)
            keys.append(Key.from_file(filename))
        names = [k.name for k in keys]
        base = self.settings.base_port
        consensus = [f"{h}:{base}" for h in hosts]
        front = [f"{h}:{base - 2000}" for h in hosts]
        mempool = [f"{h}:{base - 1000}" for h in hosts]
        committee = Committee(names, consensus, front, mempool)
        committee.print(PathMaker.committee_file())
        node_parameters.print(PathMaker.parameters_file())
        repo = self.settings.repo_name
        for i, host in enumerate(hosts):
            self.runner.run(host, f"rm -rf {repo}/.db-* {repo}/.*.json",
                            check=False)
            self.runner.put(host, PathMaker.committee_file(),
                            f"{repo}/{PathMaker.committee_file()}")
            self.runner.put(host, PathMaker.parameters_file(),
                            f"{repo}/{PathMaker.parameters_file()}")
            self.runner.put(host, key_files[i],
                            f"{repo}/{PathMaker.key_file(i)}")
        return committee

    def _check_fault_plan(self, hosts, duration, timeout_delay_ms,
                          faults=0):
        """Reject an unexecutable plan/WAN combination BEFORE any host
        boots (the LocalBench._check_fault_plan analogue: a scripted
        scenario the fleet cannot deliver must not cost a matrix run)."""
        if self.fault_plan is None or not self.fault_plan.events:
            return
        grace = 2 * timeout_delay_ms / 1000 + 3
        if self.fault_plan.max_time() > duration - grace:
            raise BenchError(
                f"fault plan's last event "
                f"(t={self.fault_plan.max_time():g}s) leaves less than "
                f"{grace:g}s of run-window headroom (duration "
                f"{duration}s) for recovery to be observable")
        alive = len(hosts) - faults
        bad = [i for i in self.fault_plan.node_indices() if i >= alive]
        if bad:
            raise BenchError(
                f"fault plan targets node(s) {bad} but only {alive} "
                "replicas will be booted (crash-fault hosts run nothing)")
        if any(e.target == "sidecar" for e in self.fault_plan.events):
            raise BenchError(
                "fault plan targets the sidecar but the remote bench "
                "boots none (sidecar faults are local-harness only for "
                "now)")
        if any(e.action == "surge" for e in self.fault_plan.events):
            raise BenchError(
                "fault plan schedules client surge events, which the "
                "remote bench cannot express yet (it does not track "
                "per-host client boot commands); run the surge scenario "
                "on the local harness")
        from ..chaos.plan import LEADER_CASCADE

        if any(e.target == LEADER_CASCADE for e in self.fault_plan.events):
            raise BenchError(
                "fault plan schedules leader-cascade events, which the "
                "remote bench cannot express yet (it has no live round "
                "estimate to pick the upcoming leaders from); run the "
                "cascade drill on the local harness")
        missing = [name for name in self.fault_plan.link_names()
                   if self.wan is None or self.wan.by_name(name) is None]
        if missing:
            raise BenchError(
                f"fault plan faults link(s) {missing} the WAN spec does "
                "not name (pass --wan with matching links)")
        if self.fault_plan.link_names():
            # A named link whose src is client/sidecar (or a dead
            # replica) lands on NO host's egress: the partition would
            # compile to zero tc commands and fail at injection time,
            # violating the validated-before-boot contract.
            from ..chaos.netem import host_links

            peers = self._wan_peers(hosts[:alive])
            carried = {
                link.label()
                for i in range(alive)
                for link, _ip, _band in host_links(
                    self.wan, f"node:{i}", peers)}
            uncarried = [name for name in self.fault_plan.link_names()
                         if name not in carried]
            if uncarried:
                raise BenchError(
                    f"fault plan faults link(s) {uncarried} that no "
                    "alive host's egress carries (src must be a booted "
                    "node:<i> or '*'; client/sidecar egress is not "
                    "shapeable on this fleet)")

    def _wan_peers(self, hosts) -> dict:
        return {f"node:{i}": host for i, host in enumerate(hosts)}

    def _check_wan(self, hosts, faults=0):
        """Reject a WAN spec the fleet cannot realize BEFORE any host
        boots.  tc shapes only ``node:<i>`` egress on this fleet, so a
        link naming sidecar/client (or a replica that will not boot)
        would compile to zero commands — and the run would still be
        recorded as WAN-shaped (wan.json written, parser notes emitted),
        publishing a clean-LAN measurement as a shaped one.  Also
        compiles every alive host's command list so a per-host band
        overflow (prio caps at 16 bands) surfaces here, not mid-fleet."""
        if self.wan is None:
            return
        from ..chaos.netem import WILDCARD, WanError, tc_setup_commands

        alive = len(hosts) - faults
        realizable = {f"node:{i}" for i in range(alive)}
        bad = sorted({
            ep for link in self.wan.links
            for ep in (link.src, link.dst)
            if ep != WILDCARD and ep not in realizable})
        if bad:
            raise BenchError(
                f"WAN spec names endpoint(s) {bad} no alive host's "
                f"egress can realize ({alive} replicas boot as "
                f"node:0..node:{alive - 1}; sidecar/client links are "
                "local-harness only)")
        peers = self._wan_peers(hosts[:alive])
        try:
            for i in range(alive):
                tc_setup_commands(self.wan, f"node:{i}", peers,
                                  dev=self.wan_dev)
        except WanError as e:
            raise BenchError(str(e))

    def _setup_wan(self, hosts):
        """Install each host's egress shaping from the spec (and tear
        down any stale qdisc first — the compiled command list leads
        with the teardown)."""
        if self.wan is None:
            return
        from ..chaos.netem import tc_setup_commands

        peers = self._wan_peers(hosts)
        Print.info(f"Shaping WAN links on {len(hosts)} host(s)...")
        for i, host in enumerate(hosts):
            for cmd in tc_setup_commands(self.wan, f"node:{i}", peers,
                                         dev=self.wan_dev):
                self.runner.run(host, cmd, timeout=60.0)

    def _teardown_wan(self, hosts):
        if self.wan is None:
            return
        from ..chaos.netem import tc_teardown_command

        for host in hosts:
            try:
                self.runner.run(host, tc_teardown_command(self.wan_dev),
                                check=False, timeout=60.0)
            except ExecutionError:
                pass  # teardown is best-effort; the next setup retries

    def _start_fault_plan(self, hosts, boots):
        if self.fault_plan is None or not self.fault_plan.events:
            return None
        from ..chaos import PlanRunner
        from .faults import RemoteFaultInjector

        Print.info(f"Executing fault plan "
                   f"({len(self.fault_plan.events)} event(s)) across "
                   "the fleet...")
        self._injector = RemoteFaultInjector(
            self.runner, hosts, self.settings.repo_name, boots,
            wan=self.wan, peers=self._wan_peers(hosts), dev=self.wan_dev)
        runner = PlanRunner(self.fault_plan, self._injector)
        runner.start()
        return runner

    def _finish_fault_plan(self, runner):
        """Stop the plan, un-pause stragglers, and hand back the
        executed events for the log step to persist.  Under-execution
        (a skipped event is a FAILED chaos run, same contract as
        LocalBench) is judged in ``run`` AFTER the logs download, so a
        stalled injection never costs the run's evidence — the partial
        chaos-events.json and node/client logs are exactly what you
        need to diagnose it."""
        if runner is None:
            return None
        runner.stop()
        runner.join(timeout=60)
        self._injector.cleanup()
        return runner.events()

    def _run_single(self, hosts, committee, rate, tx_size, faults, duration,
                    timeout, debug=False):
        Print.info(f"Running {len(hosts)} nodes (rate {rate:,} tx/s)...")
        repo = self.settings.repo_name

        # Nodes minus faults; clients only on alive hosts, waiting only on
        # alive fronts (a dead front in --nodes would block the client's
        # readiness loop forever).
        alive = len(hosts) - faults
        rate_share = -(-rate // alive) if alive else 0
        front = committee.front_addresses()[:alive]
        events = None
        # Everything from the first tc command on runs under the
        # teardown finally: a boot or shaping failure mid-fleet must
        # not leave earlier hosts' egress netem-shaped (silently
        # corrupting every later run) or their processes running.
        try:
            self._setup_wan(hosts[:alive])
            boots = {}
            for i, host in enumerate(hosts[:alive]):
                # Clean logs in a separate foreground command: the
                # background wrapper's shell opens the redirect target
                # inside logs/ before the command runs, so an
                # in-command rm would unlink it.
                self.runner.run(
                    host, f"cd {repo} && rm -rf {PathMaker.logs_path()} && "
                          f"mkdir -p {PathMaker.logs_path()}")
                cmd = (f"cd {repo} && "
                       + CommandMaker.run_client(
                           front[i], tx_size, rate_share, timeout,
                           nodes=front))
                self.runner.run_background(
                    host, cmd, f"{repo}/{PathMaker.client_log_file(i)}")
            for i, host in enumerate(hosts[:alive]):
                cmd = (f"cd {repo} && "
                       + CommandMaker.run_node(
                           PathMaker.key_file(i), PathMaker.committee_file(),
                           PathMaker.db_path(i), PathMaker.parameters_file(),
                           debug=debug))
                boots[i] = (cmd, f"{repo}/{PathMaker.node_log_file(i)}")
                self.runner.run_background(host, cmd, boots[i][1])

            # Same plan-origin convention as the local harness: event
            # times offset from the moment clients start being paced.
            sleep(2 * timeout / 1000)
            plan_runner = self._start_fault_plan(hosts[:alive], boots)
            sleep(duration)
            events = self._finish_fault_plan(plan_runner)
        finally:
            self._teardown_wan(hosts[:alive])
            self.kill(hosts)
        return events

    def kill(self, hosts=None):
        """Stop every node/client process on the fleet (fabfile kill)."""
        for host in hosts if hosts is not None else self.hosts:
            # Bracketed dot so the pattern never matches the ssh
            # wrapper shell carrying it (see faults.NODE_PATTERN).
            self.runner.run(host, "pkill -f '[.]/node run'", check=False,
                            timeout=60.0)
            self.runner.run(host, "pkill -f '[.]/client '", check=False,
                            timeout=60.0)

    def _clock_offsets(self, hosts):
        """grafttrace: estimate each host's wall-clock offset through
        the ssh transport (RTT-midpoint probes, obs/trace.py) and
        persist logs/clock-offsets.json keyed by log file name, so the
        trace merger aligns per-host TRACE stamps before stitching.
        Best-effort: an unreachable host contributes offset 0."""
        from time import time as wall

        from ..obs.trace import probe_host_offset

        offsets = {}
        for i, host in enumerate(hosts):
            try:
                # A clock probe is a sub-second `date`: a tight timeout
                # bounds what a dead host can cost the log-collection
                # path (probe_host_offset also bails after one failed
                # dial when no probe has succeeded yet).
                off = probe_host_offset(
                    lambda h, c: self.runner.run(
                        h, c, timeout=10.0).stdout,
                    host, clock=wall, samples=3)
            except (ExecutionError, FabricError):
                continue
            if off:
                offsets[f"node-{i}.log"] = round(off, 6)
        if offsets:
            with open(PathMaker.clock_offsets_file(), "w") as f:
                json.dump(offsets, f)

    def _logs(self, hosts, faults, chaos_events=None):
        subprocess.run(["/bin/sh", "-c", CommandMaker.clean_logs()],
                       check=True)
        repo = self.settings.repo_name
        alive = hosts[:len(hosts) - faults]  # faulty hosts ran nothing
        for i, host in enumerate(
                progress_bar(alive, prefix="Downloading logs:")):
            self.runner.get(host, f"{repo}/{PathMaker.node_log_file(i)}",
                            PathMaker.node_log_file(i))
            self.runner.get(host, f"{repo}/{PathMaker.client_log_file(i)}",
                            PathMaker.client_log_file(i))
        self._clock_offsets(alive)
        # The same on-disk contract as the local harness: the parser
        # reads chaos-events.json / wan.json / slo.json from the logs
        # dir and switches into chaos mode (recovery + SLO verdicts,
        # strict assertions) when they exist.
        if chaos_events is not None:
            with open(PathMaker.chaos_events_file(), "w") as f:
                json.dump(chaos_events, f)
        if self.wan is not None:
            with open(PathMaker.wan_file(), "w") as f:
                json.dump(self.wan.to_json(), f)
        with open(PathMaker.slo_file(), "w") as f:
            json.dump(self.slos, f)
        return LogParser.process(PathMaker.logs_path(), faults=faults)

    def run(self, bench_parameters, node_parameters, debug=False):
        """Full matrix: nodes x rate x runs, appending to result files
        (remote.py:245-300 analogue)."""
        Print.heading("Starting remote benchmark")
        # grafttrace: fleet runs trace by default too (same setdefault
        # contract as LocalBench — an explicit "trace": false wins).
        node_parameters.json.setdefault("trace", True)
        for n in bench_parameters.nodes:
            hosts = self.hosts[:n]
            if len(hosts) < n:
                Print.warn(f"only {len(hosts)} hosts for {n}-node run; "
                           "skipping")
                continue
            self._check_fault_plan(
                hosts, bench_parameters.duration,
                node_parameters.timeout_delay,
                faults=bench_parameters.faults)
            self._check_wan(hosts, faults=bench_parameters.faults)
            committee = self._config(hosts, node_parameters)
            for rate in bench_parameters.rate:
                for run in range(bench_parameters.runs):
                    Print.heading(
                        f"Run {run + 1}/{bench_parameters.runs}: "
                        f"{n} nodes, {rate:,} tx/s")
                    try:
                        events = self._run_single(
                            hosts, committee, rate,
                            bench_parameters.tx_size,
                            bench_parameters.faults,
                            bench_parameters.duration,
                            node_parameters.timeout_delay, debug)
                        parser = self._logs(hosts, bench_parameters.faults,
                                            chaos_events=events)
                        # Judge under-execution AFTER the logs download
                        # (the partial chaos-events.json is the
                        # diagnosis evidence) but BEFORE the result file
                        # is published: a run whose scripted scenario
                        # never finished must not aggregate as a
                        # passing chaos cell.
                        if events is not None and \
                                len(events) < len(self.fault_plan.events):
                            raise BenchError(
                                f"fault plan executed only {len(events)} "
                                f"of {len(self.fault_plan.events)} "
                                "event(s) before the run window closed "
                                "(an earlier injection stalled?)")
                        parser.print(PathMaker.result_file(
                            bench_parameters.faults, n, rate,
                            bench_parameters.tx_size,
                            chain=node_parameters.json["consensus"].get(
                                "chain_depth", 2)))
                    except (ExecutionError, FabricError, ParseError,
                            BenchError) as e:
                        # A failed run must not abort the matrix: print,
                        # skip this cell, keep the downloaded evidence.
                        Print.error(e if isinstance(e, BenchError)
                                    else BenchError("Benchmark failed", e))
                        continue

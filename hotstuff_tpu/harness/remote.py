"""Remote (multi-host) benchmark orchestration over plain ssh/scp.

Capability mirror of benchmark/benchmark/remote.py:31-300 — install,
update, configure, run, and collect logs across a fleet of hosts — built
on subprocess ssh instead of fabric/paramiko (neither ships in this
image). Hosts come from a `hosts` list in settings.json or an explicit
list; cloud instance lifecycle (create/start/stop/terminate) lives in
instance.py and is gated on boto3 availability.
"""

from __future__ import annotations

import subprocess
from os.path import join

from .commands import CommandMaker
from .config import Committee, Key
from .logs import LogParser, ParseError
from .utils import BenchError, PathMaker, Print, progress_bar


class FabricError(Exception):
    """SSH transport failure (name kept for parity with the reference's
    error taxonomy)."""


class ExecutionError(Exception):
    pass


class RemoteRunner:
    """Thin ssh/scp wrapper used by Bench below."""

    def __init__(self, user, key_path, connect_timeout=10):
        self.user = user
        self.key_path = key_path
        self.connect_timeout = connect_timeout

    def _ssh_base(self, host):
        return [
            "ssh", "-i", self.key_path,
            "-o", "StrictHostKeyChecking=no",
            "-o", f"ConnectTimeout={self.connect_timeout}",
            f"{self.user}@{host}",
        ]

    def run(self, host, command, check=True, hide=True):
        result = subprocess.run(
            self._ssh_base(host) + [command],
            capture_output=hide, text=True)
        if check and result.returncode != 0:
            raise ExecutionError(
                f"[{host}] {command!r} failed: {result.stderr}")
        return result

    def run_background(self, host, command, log_file):
        # nohup + setsid so the process survives the ssh session.
        wrapped = (f"nohup setsid sh -c '{command}' > {log_file} 2>&1 "
                   f"< /dev/null &")
        return self.run(host, wrapped)

    def put(self, host, local, remote):
        result = subprocess.run(
            ["scp", "-i", self.key_path, "-o", "StrictHostKeyChecking=no",
             local, f"{self.user}@{host}:{remote}"],
            capture_output=True, text=True)
        if result.returncode != 0:
            raise FabricError(f"scp to {host} failed: {result.stderr}")

    def get(self, host, remote, local):
        result = subprocess.run(
            ["scp", "-i", self.key_path, "-o", "StrictHostKeyChecking=no",
             f"{self.user}@{host}:{remote}", local],
            capture_output=True, text=True)
        if result.returncode != 0:
            raise FabricError(f"scp from {host} failed: {result.stderr}")


class Bench:
    """Multi-host benchmark: one node per host, one client per node."""

    def __init__(self, settings, hosts, user="ubuntu"):
        self.settings = settings
        self.hosts = hosts
        self.runner = RemoteRunner(user, settings.key_path)

    def install(self):
        """Install the toolchain + clone the repo on every host
        (remote.py:52-81 analogue, apt/cmake instead of rustup)."""
        cmd = " && ".join([
            "sudo apt-get update",
            "sudo apt-get -y install build-essential cmake ninja-build "
            "python3 python3-pip",
            f"(git clone {self.settings.repo_url} || true)",
        ])
        for host in progress_bar(self.hosts, prefix="Installing:"):
            self.runner.run(host, cmd)

    def update(self):
        """Pull + rebuild on every host (remote.py:115-130 analogue)."""
        repo = self.settings.repo_name
        cmd = " && ".join([
            f"cd {repo}",
            f"git fetch -f && git checkout -f {self.settings.branch}",
            "git pull -f",
            CommandMaker.compile(),
        ])
        for host in progress_bar(self.hosts, prefix="Updating:"):
            self.runner.run(host, cmd)

    def _config(self, hosts, node_parameters):
        """Generate keys locally, build the committee from host IPs, upload
        configs (remote.py:132-177 analogue)."""
        subprocess.run(["/bin/sh", "-c", CommandMaker.cleanup()], check=False)
        keys = []
        key_files = [PathMaker.key_file(i) for i in range(len(hosts))]
        for filename in key_files:
            subprocess.run(
                ["/bin/sh", "-c",
                 join(PathMaker.binary_path(), "node")
                 + f" keys --filename {filename}"],
                check=True)
            keys.append(Key.from_file(filename))
        names = [k.name for k in keys]
        base = self.settings.base_port
        consensus = [f"{h}:{base}" for h in hosts]
        front = [f"{h}:{base - 2000}" for h in hosts]
        mempool = [f"{h}:{base - 1000}" for h in hosts]
        committee = Committee(names, consensus, front, mempool)
        committee.print(PathMaker.committee_file())
        node_parameters.print(PathMaker.parameters_file())
        repo = self.settings.repo_name
        for i, host in enumerate(hosts):
            self.runner.run(host, f"rm -rf {repo}/.db-* {repo}/.*.json",
                            check=False)
            self.runner.put(host, PathMaker.committee_file(),
                            f"{repo}/{PathMaker.committee_file()}")
            self.runner.put(host, PathMaker.parameters_file(),
                            f"{repo}/{PathMaker.parameters_file()}")
            self.runner.put(host, key_files[i],
                            f"{repo}/{PathMaker.key_file(i)}")
        return committee

    def _run_single(self, hosts, committee, rate, tx_size, faults, duration,
                    timeout, debug=False):
        Print.info(f"Running {len(hosts)} nodes (rate {rate:,} tx/s)...")
        repo = self.settings.repo_name

        # Nodes minus faults; clients only on alive hosts, waiting only on
        # alive fronts (a dead front in --nodes would block the client's
        # readiness loop forever).
        alive = len(hosts) - faults
        rate_share = -(-rate // alive) if alive else 0
        front = committee.front_addresses()[:alive]
        for i, host in enumerate(hosts[:alive]):
            # Clean logs in a separate foreground command: the background
            # wrapper's shell opens the redirect target inside logs/ before
            # the command runs, so an in-command rm would unlink it.
            self.runner.run(
                host, f"cd {repo} && rm -rf {PathMaker.logs_path()} && "
                      f"mkdir -p {PathMaker.logs_path()}")
            cmd = (f"cd {repo} && "
                   + CommandMaker.run_client(
                       front[i], tx_size, rate_share, timeout, nodes=front))
            self.runner.run_background(
                host, cmd, f"{repo}/{PathMaker.client_log_file(i)}")
        for i, host in enumerate(hosts[:alive]):
            cmd = (f"cd {repo} && "
                   + CommandMaker.run_node(
                       PathMaker.key_file(i), PathMaker.committee_file(),
                       PathMaker.db_path(i), PathMaker.parameters_file(),
                       debug=debug))
            self.runner.run_background(
                host, cmd, f"{repo}/{PathMaker.node_log_file(i)}")

        from time import sleep

        sleep(2 * timeout / 1000 + duration)
        self.kill(hosts)

    def kill(self, hosts=None):
        """Stop every node/client process on the fleet (fabfile kill)."""
        for host in hosts if hosts is not None else self.hosts:
            self.runner.run(host, "pkill -f './node run'", check=False)
            self.runner.run(host, "pkill -f './client '", check=False)

    def _logs(self, hosts, faults):
        subprocess.run(["/bin/sh", "-c", CommandMaker.clean_logs()],
                       check=True)
        repo = self.settings.repo_name
        alive = hosts[:len(hosts) - faults]  # faulty hosts ran nothing
        for i, host in enumerate(
                progress_bar(alive, prefix="Downloading logs:")):
            self.runner.get(host, f"{repo}/{PathMaker.node_log_file(i)}",
                            PathMaker.node_log_file(i))
            self.runner.get(host, f"{repo}/{PathMaker.client_log_file(i)}",
                            PathMaker.client_log_file(i))
        return LogParser.process(PathMaker.logs_path(), faults=faults)

    def run(self, bench_parameters, node_parameters, debug=False):
        """Full matrix: nodes x rate x runs, appending to result files
        (remote.py:245-300 analogue)."""
        Print.heading("Starting remote benchmark")
        for n in bench_parameters.nodes:
            hosts = self.hosts[:n]
            if len(hosts) < n:
                Print.warn(f"only {len(hosts)} hosts for {n}-node run; "
                           "skipping")
                continue
            committee = self._config(hosts, node_parameters)
            for rate in bench_parameters.rate:
                for run in range(bench_parameters.runs):
                    Print.heading(
                        f"Run {run + 1}/{bench_parameters.runs}: "
                        f"{n} nodes, {rate:,} tx/s")
                    try:
                        self._run_single(
                            hosts, committee, rate,
                            bench_parameters.tx_size,
                            bench_parameters.faults,
                            bench_parameters.duration,
                            node_parameters.timeout_delay, debug)
                        parser = self._logs(hosts, bench_parameters.faults)
                        parser.print(PathMaker.result_file(
                            bench_parameters.faults, n, rate,
                            bench_parameters.tx_size,
                            chain=node_parameters.json["consensus"].get(
                                "chain_depth", 2)))
                    except (ExecutionError, FabricError, ParseError) as e:
                        Print.error(BenchError("Benchmark failed", e))
                        continue

"""Shell command strings for the benchmark harness
(benchmark/benchmark/commands.py:6-56 capability: compile, keygen, run
node/client, cleanup, binary aliases) — targeting the C++ CMake build
instead of cargo.
"""

from __future__ import annotations

from os.path import join

from .utils import PathMaker


class CommandMaker:
    @staticmethod
    def cleanup():
        return (
            f"rm -rf .db-* ; rm -f .*.json ; "
            f"mkdir -p {PathMaker.results_path()}"
        )

    @staticmethod
    def clean_logs():
        return f"rm -rf {PathMaker.logs_path()} ; mkdir -p {PathMaker.logs_path()}"

    @staticmethod
    def compile():
        # A build dir configured with a different generator (or a stale
        # toolchain path) makes `cmake -G Ninja` fail on its cache; wipe the
        # cache and reconfigure instead of aborting the whole benchmark.
        src, bld = PathMaker.node_crate_path(), PathMaker.binary_path()
        cfg = f"cmake -S {src} -B {bld} -G Ninja"
        # No cmake in the environment (e.g. the CI container builds the
        # binaries with scripts/native_sanitize.sh-style direct g++): accept
        # prebuilt node+client in the build dir instead of aborting the run.
        return (
            f"if command -v cmake >/dev/null 2>&1 ; then "
            f"( {cfg} || {{ rm -rf {bld}/CMakeCache.txt {bld}/CMakeFiles "
            f"&& {cfg} ; }} ) && cmake --build {bld} ; "
            f"else test -x {bld}/node && test -x {bld}/client ; fi"
        )

    @staticmethod
    def generate_key(filename):
        assert isinstance(filename, str)
        return f"./node keys --filename {filename}"

    @staticmethod
    def run_node(keys, committee, store, parameters, debug=False):
        assert isinstance(keys, str)
        assert isinstance(committee, str)
        assert isinstance(parameters, str)
        assert isinstance(debug, bool)
        v = "-vv" if debug else "-v"
        return (
            f"./node run --keys {keys} --committee {committee} "
            f"--store {store} --parameters {parameters} {v}"
        )

    @staticmethod
    def run_client(address, size, rate, timeout, nodes=None, users=None,
                   seed=None, sign=False, forge_pct=None, user_offset=None,
                   sample_offset=None):
        """``users``/``seed`` opt into the graftsurge multi-user
        heavy-tailed generator (client --users/--seed); omitted, the
        client keeps its legacy constant-rate stream.  ``sign`` opts
        into graftingress signed-transaction frames (per-user Ed25519,
        derived from the seed); ``forge_pct`` flips a signature bit on
        that percentage of filler txs; the offsets shard the user-id
        and sample-id spaces across multi-process client shards."""
        assert isinstance(address, str)
        assert isinstance(size, int) and size > 0
        assert isinstance(rate, int) and rate >= 0
        assert isinstance(nodes, list) or nodes is None
        assert users is None or (isinstance(users, int) and users > 0)
        assert seed is None or isinstance(seed, int)
        assert forge_pct is None or \
            (isinstance(forge_pct, (int, float)) and 0 <= forge_pct <= 100)
        assert user_offset is None or \
            (isinstance(user_offset, int) and user_offset >= 0)
        assert sample_offset is None or \
            (isinstance(sample_offset, int) and sample_offset >= 0)
        nodes = nodes or []
        assert all(isinstance(x, str) for x in nodes)
        nodes_str = f" --nodes {' '.join(nodes)}" if nodes else ""
        users_str = f" --users {users}" if users else ""
        seed_str = f" --seed {seed}" if seed is not None else ""
        sign_str = " --sign" if sign else ""
        forge_str = f" --forge-pct {forge_pct:g}" if forge_pct else ""
        uoff_str = f" --user-offset {user_offset}" \
            if user_offset else ""
        soff_str = f" --sample-offset {sample_offset}" \
            if sample_offset else ""
        return (
            f"./client {address} --size {size} "
            f"--rate {rate} --timeout {timeout}{users_str}{seed_str}"
            f"{sign_str}{forge_str}{uoff_str}{soff_str}"
            f"{nodes_str}"
        )

    @staticmethod
    def run_sidecar(port, log_path):
        return (
            f"python -m hotstuff_tpu.sidecar --port {port} "
            f"> {log_path} 2>&1"
        )

    @staticmethod
    def kill():
        return "tmux kill-server 2>/dev/null || true"

    @staticmethod
    def alias_binaries(origin):
        assert isinstance(origin, str)
        node, client = join(origin, "node"), join(origin, "client")
        return f"rm -f node client ; ln -s {node} . ; ln -s {client} ."

"""Aggregate raw result files (mean ± stdev across runs) into plot series.

Capability mirror of benchmark/benchmark/aggregate.py:80-174: scans
results/bench-*.txt, groups runs of the same configuration, and emits
latency-vs-rate, tps-vs-committee-size, and robustness series under
plots/.
"""

from __future__ import annotations

import os
from collections import defaultdict
from glob import glob
from os.path import join
from re import search
from statistics import mean, stdev

from .utils import PathMaker


class Setup:
    def __init__(self, faults, nodes, rate, tx_size):
        self.faults = faults
        self.nodes = nodes
        self.rate = rate
        self.tx_size = tx_size
        self.max_latency = None

    def __str__(self):
        return (
            f" Faults: {self.faults}\n"
            f" Committee size: {self.nodes}\n"
            f" Input rate: {self.rate} tx/s\n"
            f" Transaction size: {self.tx_size} B\n"
            f" Max latency: {self.max_latency} ms\n"
        )

    def __eq__(self, other):
        return isinstance(other, Setup) and str(self) == str(other)

    def __hash__(self):
        return hash(str(self))

    @classmethod
    def from_str(cls, raw):
        faults = int(search(r"Faults: (\d+)", raw).group(1))
        nodes = int(search(r"Committee size: (\d+)", raw).group(1))
        rate = int(search(r"Input rate: ([\d,]+)", raw).group(1).replace(",", ""))
        tx_size = int(
            search(r"Transaction size: ([\d,]+)", raw).group(1).replace(",", ""))
        return cls(faults, nodes, rate, tx_size)


class Result:
    def __init__(self, mean_tps, mean_latency, std_tps=0, std_latency=0):
        self.mean_tps = mean_tps
        self.mean_latency = mean_latency
        self.std_tps = std_tps
        self.std_latency = std_latency

    def __str__(self):
        return (
            f" TPS: {self.mean_tps} +/- {self.std_tps} tx/s\n"
            f" Latency: {self.mean_latency} +/- {self.std_latency} ms\n"
        )

    @classmethod
    def from_str(cls, raw):
        tps = int(
            search(r"End-to-end TPS: ([\d,]+)", raw).group(1).replace(",", ""))
        latency = int(
            search(r"End-to-end latency: ([\d,]+)", raw).group(1)
            .replace(",", ""))
        return cls(tps, latency)

    @classmethod
    def aggregate(cls, results):
        assert len(results) > 0
        if len(results) == 1:
            return results[0]
        mean_tps = round(mean(r.mean_tps for r in results))
        mean_latency = round(mean(r.mean_latency for r in results))
        std_tps = round(stdev(r.mean_tps for r in results))
        std_latency = round(stdev(r.mean_latency for r in results))
        return cls(mean_tps, mean_latency, std_tps, std_latency)


class LogAggregator:
    def __init__(self, max_latencies=None):
        self.max_latencies = max_latencies or []
        data = ""
        for filename in glob(join(PathMaker.results_path(), "bench-*.txt")):
            # Chain-tagged files (bench-3chain-...) are a different commit
            # rule with +1 round of latency; the SUMMARY grammar is frozen
            # (no chain field), so keep them out of the default series
            # instead of averaging two protocols into one record.
            if search(r"bench-\d+chain-", os.path.basename(filename)):
                continue
            with open(filename, "r") as f:
                data += f.read()

        records = defaultdict(list)
        for chunk in data.replace(",", "").split("SUMMARY")[1:]:
            if not chunk:
                continue
            # Failed runs (zero execution time / zero TPS) would silently
            # drag every averaged series down; reject them here instead of
            # trusting result files to be hand-curated.
            exec_time = search(r"Execution time: (\d+)", chunk)
            result = Result.from_str(chunk)
            if (exec_time and int(exec_time.group(1)) == 0) or \
                    result.mean_tps == 0:
                continue
            records[Setup.from_str(chunk)].append(result)

        self.records = {k: Result.aggregate(v) for k, v in records.items()}

    def print(self):
        os.makedirs(PathMaker.plot_path(), exist_ok=True)
        results = [
            self._print_latency(),
            self._print_tps(scalability=False),
            self._print_tps(scalability=True),
            self._print_robustness(),
        ]
        for name, records in results:
            for setup, values in records.items():
                data = "\n".join(f" Variable value: X={x}\n{y}"
                                 for x, y in values)
                string = (
                    "\n"
                    "-----------------------------------------\n"
                    " RESULTS:\n"
                    "-----------------------------------------\n"
                    f"{setup}"
                    "\n"
                    f"{data}"
                    "-----------------------------------------\n"
                )
                max_lat = f"-{setup.max_latency}" if setup.max_latency else ""
                filename = join(
                    PathMaker.plot_path(),
                    f"{name}-{setup.faults}-{setup.nodes}-{setup.rate}-"
                    f"{setup.tx_size}{max_lat}.txt".replace("[", "")
                    .replace("]", "").replace(" ", ""))
                with open(filename, "w") as f:
                    f.write(string)

    def _print_latency(self):
        """Latency as a function of input rate, per committee size."""
        organized = defaultdict(list)
        for setup, result in self.records.items():
            rate = setup.rate
            setup_key = Setup(setup.faults, setup.nodes, "any", setup.tx_size)
            organized[setup_key].append((rate, result))
        for setup_key in organized:
            organized[setup_key].sort(key=lambda x: x[0])
        return "latency", organized

    def _print_tps(self, scalability):
        """Peak TPS under a latency cap, vs committee size (scalability) or
        vs rate."""
        organized = defaultdict(list)
        for max_latency in self.max_latencies:
            for setup, result in self.records.items():
                if result.mean_latency <= max_latency:
                    nodes = setup.nodes
                    rate = setup.rate
                    key = Setup(setup.faults, "x" if scalability else nodes,
                                "any", setup.tx_size)
                    key.max_latency = max_latency
                    variable = nodes if scalability else rate
                    organized[key].append((variable, result))
        # keep the best TPS per variable value
        for key, values in organized.items():
            values.sort(key=lambda x: (x[0], x[1].mean_tps))
            best = {}
            for variable, result in values:
                best[variable] = result
            organized[key] = sorted(best.items())
        return ("tps-scalability" if scalability else "tps"), organized

    def _print_robustness(self):
        """TPS/latency as input rate grows (stress behavior)."""
        organized = defaultdict(list)
        for setup, result in self.records.items():
            rate = setup.rate
            key = Setup(setup.faults, setup.nodes, "any", setup.tx_size)
            organized[key].append((rate, result))
        for key in organized:
            organized[key].sort(key=lambda x: x[0])
        return "robustness", organized

"""Aggregate raw result files (mean ± stdev across runs) into plot series.

Capability mirror of benchmark/benchmark/aggregate.py:80-174: scans
results/bench-*.txt, groups runs of the same configuration, and emits
latency-vs-rate, tps-vs-committee-size, and robustness series under
plots/.

graftwan adds the matrix path: ``print_matrix`` folds every aggregated
cell into one nodes×rate table per (faults, tx size) — the reference's
headline artifact shape (SURVEY.md §3.5/§6) — as ``plots/matrix-*.txt``
(a peak-TPS table in the §6 baseline-table column order, so TPU-build
numbers sit next to the paper's) plus machine-readable
``plots/matrix.json``.  Chaos columns ride along: runs whose result
files carry graftchaos/SLO notes report per-cell SLO pass/fail counts
and the WAN shape they were measured under, so a shaped or faulted
cell never masquerades as a clean-LAN number.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from glob import glob
from os.path import join
from re import findall, search
from statistics import mean, stdev

from .utils import PathMaker


class Setup:
    def __init__(self, faults, nodes, rate, tx_size, chaos=False):
        self.faults = faults
        self.nodes = nodes
        self.rate = rate
        self.tx_size = tx_size
        self.chaos = chaos  # scripted-fault/WAN run: aggregated apart
        self.max_latency = None

    def __str__(self):
        return (
            f" Faults: {self.faults}\n"
            f" Committee size: {self.nodes}\n"
            f" Input rate: {self.rate} tx/s\n"
            f" Transaction size: {self.tx_size} B\n"
            f" Scripted chaos/WAN: {self.chaos}\n"
            f" Max latency: {self.max_latency} ms\n"
        )

    def __eq__(self, other):
        return isinstance(other, Setup) and str(self) == str(other)

    def __hash__(self):
        return hash(str(self))

    @classmethod
    def from_str(cls, raw):
        faults = int(search(r"Faults: (\d+)", raw).group(1))
        nodes = int(search(r"Committee size: (\d+)", raw).group(1))
        rate = int(search(r"Input rate: ([\d,]+)", raw).group(1).replace(",", ""))
        tx_size = int(
            search(r"Transaction size: ([\d,]+)", raw).group(1).replace(",", ""))
        return cls(faults, nodes, rate, tx_size)


class Result:
    def __init__(self, mean_tps, mean_latency, std_tps=0, std_latency=0,
                 runs=1):
        self.mean_tps = mean_tps
        self.mean_latency = mean_latency
        self.std_tps = std_tps
        self.std_latency = std_latency
        # Repeatability (VERDICT r5 "do this" #4): how many same-settings
        # runs this mean±stdev aggregates — a band over one run is a
        # point estimate wearing a costume, and the artifacts must say
        # which one they are quoting.
        self.runs = runs

    def __str__(self):
        # " TPS: m +/- s tx/s" prefix is frozen (plot.py findall); the
        # run count rides after it.
        return (
            f" TPS: {self.mean_tps} +/- {self.std_tps} tx/s "
            f"over {self.runs} run(s)\n"
            f" Latency: {self.mean_latency} +/- {self.std_latency} ms\n"
        )

    @classmethod
    def from_str(cls, raw):
        tps = int(
            search(r"End-to-end TPS: ([\d,]+)", raw).group(1).replace(",", ""))
        latency = int(
            search(r"End-to-end latency: ([\d,]+)", raw).group(1)
            .replace(",", ""))
        return cls(tps, latency)

    @classmethod
    def aggregate(cls, results):
        assert len(results) > 0
        if len(results) == 1:
            return results[0]
        mean_tps = round(mean(r.mean_tps for r in results))
        mean_latency = round(mean(r.mean_latency for r in results))
        std_tps = round(stdev(r.mean_tps for r in results))
        std_latency = round(stdev(r.mean_latency for r in results))
        return cls(mean_tps, mean_latency, std_tps, std_latency,
                   runs=len(results))


class LogAggregator:
    def __init__(self, max_latencies=None):
        self.max_latencies = max_latencies or []
        data = ""
        for filename in glob(join(PathMaker.results_path(), "bench-*.txt")):
            # Chain-tagged files (bench-3chain-...) are a different commit
            # rule with +1 round of latency; the SUMMARY grammar is frozen
            # (no chain field), so keep them out of the default series
            # instead of averaging two protocols into one record.
            if search(r"bench-\d+chain-", os.path.basename(filename)):
                continue
            with open(filename, "r") as f:
                data += f.read()

        records = defaultdict(list)
        chaos = defaultdict(lambda: {"slo_pass": 0, "slo_fail": 0,
                                     "runs_with_chaos": 0, "wan": None})
        for chunk in data.replace(",", "").split("SUMMARY")[1:]:
            if not chunk:
                continue
            # Failed runs (zero execution time / zero TPS) would silently
            # drag every averaged series down; reject them here instead of
            # trusting result files to be hand-curated.
            exec_time = search(r"Execution time: (\d+)", chunk)
            result = Result.from_str(chunk)
            if (exec_time and int(exec_time.group(1)) == 0) or \
                    result.mean_tps == 0:
                continue
            setup = Setup.from_str(chunk)
            # graftwan: mine the chaos/SLO notes the LogParser wrote so
            # the matrix can mark which cells ran faulted/shaped.  The
            # chaos-ness is part of the Setup IDENTITY: a clean and a
            # shaped/faulted run of the same configuration must never
            # be averaged into one mean (the docstring's no-masquerade
            # contract).
            verdicts = findall(r"Chaos SLO [\w-]+: .*?(PASS|FAIL)", chunk)
            wan = search(r"WAN: (\d+ shaped link[^\n]*)", chunk)
            setup.chaos = bool(
                verdicts or wan
                or search(r"Chaos plan: \d+ event", chunk))
            records[setup].append(result)
            if setup.chaos:
                cell = chaos[setup]
                cell["runs_with_chaos"] += 1
                cell["slo_pass"] += sum(1 for v in verdicts if v == "PASS")
                cell["slo_fail"] += sum(1 for v in verdicts if v == "FAIL")
                if wan:
                    cell["wan"] = wan.group(1).strip()

        self.records = {k: Result.aggregate(v) for k, v in records.items()}
        self.chaos = {k: dict(v) for k, v in chaos.items()
                      if v["runs_with_chaos"] or v["wan"]}

    def print(self):
        os.makedirs(PathMaker.plot_path(), exist_ok=True)
        results = [
            self._print_latency(),
            self._print_tps(scalability=False),
            self._print_tps(scalability=True),
            self._print_robustness(),
        ]
        for name, records in results:
            for setup, values in records.items():
                data = "\n".join(f" Variable value: X={x}\n{y}"
                                 for x, y in values)
                string = (
                    "\n"
                    "-----------------------------------------\n"
                    " RESULTS:\n"
                    "-----------------------------------------\n"
                    f"{setup}"
                    "\n"
                    f"{data}"
                    "-----------------------------------------\n"
                )
                max_lat = f"-{setup.max_latency}" if setup.max_latency else ""
                chaos_tag = "-chaos" if setup.chaos else ""
                filename = join(
                    PathMaker.plot_path(),
                    f"{name}-{setup.faults}-{setup.nodes}-{setup.rate}-"
                    f"{setup.tx_size}{max_lat}{chaos_tag}.txt"
                    .replace("[", "").replace("]", "").replace(" ", ""))
                with open(filename, "w") as f:
                    f.write(string)

    def _print_latency(self):
        """Latency as a function of input rate, per committee size."""
        organized = defaultdict(list)
        for setup, result in self.records.items():
            rate = setup.rate
            setup_key = Setup(setup.faults, setup.nodes, "any",
                              setup.tx_size, chaos=setup.chaos)
            organized[setup_key].append((rate, result))
        for setup_key in organized:
            organized[setup_key].sort(key=lambda x: x[0])
        return "latency", organized

    def _print_tps(self, scalability):
        """Peak TPS under a latency cap, vs committee size (scalability) or
        vs rate."""
        organized = defaultdict(list)
        for max_latency in self.max_latencies:
            for setup, result in self.records.items():
                if result.mean_latency <= max_latency:
                    nodes = setup.nodes
                    rate = setup.rate
                    key = Setup(setup.faults, "x" if scalability else nodes,
                                "any", setup.tx_size, chaos=setup.chaos)
                    key.max_latency = max_latency
                    variable = nodes if scalability else rate
                    organized[key].append((variable, result))
        # keep the best TPS per variable value
        for key, values in organized.items():
            values.sort(key=lambda x: (x[0], x[1].mean_tps))
            best = {}
            for variable, result in values:
                best[variable] = result
            organized[key] = sorted(best.items())
        return ("tps-scalability" if scalability else "tps"), organized

    def _print_robustness(self):
        """TPS/latency as input rate grows (stress behavior)."""
        organized = defaultdict(list)
        for setup, result in self.records.items():
            rate = setup.rate
            key = Setup(setup.faults, setup.nodes, "any",
                        setup.tx_size, chaos=setup.chaos)
            organized[key].append((rate, result))
        for key in organized:
            organized[key].sort(key=lambda x: x[0])
        return "robustness", organized

    # -- repeatability bands (VERDICT r5 "do this" #4) -----------------------

    def bands(self, min_runs: int = 2) -> list:
        """Per-setup repeatability bands from multi-run same-settings
        result files: every configuration with >= ``min_runs`` aggregated
        runs, as JSON-safe dicts quoting mean±stdev — the shape
        results/README's committee rows should be quoted in (a band,
        not a point estimate)."""
        out = []
        for setup, result in sorted(
                self.records.items(),
                key=lambda kv: (kv[0].faults, kv[0].nodes, kv[0].rate)):
            if result.runs < min_runs:
                continue
            out.append({
                "faults": setup.faults, "nodes": setup.nodes,
                "rate": setup.rate, "tx_size": setup.tx_size,
                "chaos": setup.chaos, "runs": result.runs,
                "tps": result.mean_tps, "tps_std": result.std_tps,
                "latency_ms": result.mean_latency,
                "latency_std": result.std_latency,
            })
        return out

    def print_bands(self, min_runs: int = 2):
        """Human-readable repeatability table on stdout (the aggregate
        CLI surfaces it so quoting a band is copy-paste, not archaeology
        over result files)."""
        bands = self.bands(min_runs=min_runs)
        if not bands:
            print(f"no setup has >= {min_runs} same-settings runs yet "
                  "(repeatability bands need repeats)")
            return
        print("Repeatability bands (mean +/- stdev over same-settings "
              "runs):")
        for b in bands:
            chaos = " [chaos]" if b["chaos"] else ""
            print(f"  N={b['nodes']} f={b['faults']} rate={b['rate']:,}"
                  f"{chaos}: {b['tps']:,} +/- {b['tps_std']:,} tx/s, "
                  f"{b['latency_ms']:,} +/- {b['latency_std']:,} ms "
                  f"over {b['runs']} runs")

    # -- graftwan matrix ----------------------------------------------------

    def matrix(self) -> dict:
        """Every aggregated cell as one nodes×rate matrix per
        (faults, tx_size) — the reference's headline artifact shape::

            {(faults, tx_size): {"nodes": [...], "rates": [...],
                                 "cells": {(nodes, rate): {...}}}}

        Cell dicts are JSON-safe (tps/latency ± stdev, plus the chaos
        summary mined from the result files when the run was faulted or
        WAN-shaped).
        """
        out = {}
        for setup, result in self.records.items():
            key = (setup.faults, setup.tx_size)
            group = out.setdefault(
                key, {"nodes": set(), "rates": set(), "cells": {}})
            group["nodes"].add(setup.nodes)
            group["rates"].add(setup.rate)
            cell = {
                "tps": result.mean_tps, "tps_std": result.std_tps,
                "latency_ms": result.mean_latency,
                "latency_std": result.std_latency,
                "runs": result.runs,
            }
            if setup in self.chaos:
                cell["chaos"] = self.chaos[setup]
            # Clean and chaos runs of the same cell aggregate apart;
            # when both exist, the clean mean owns the grid slot and the
            # chaos mean rides along under "chaos_run" (never averaged).
            slot = group["cells"].get((setup.nodes, setup.rate))
            if slot is None:
                group["cells"][(setup.nodes, setup.rate)] = cell
            elif "chaos" in cell:
                slot["chaos_run"] = cell
            else:
                cell["chaos_run"] = slot
                group["cells"][(setup.nodes, setup.rate)] = cell
        for group in out.values():
            group["nodes"] = sorted(group["nodes"])
            group["rates"] = sorted(group["rates"])
        return out

    def print_matrix(self):
        """Write the nodes×rate matrix artifacts: one human-readable
        ``plots/matrix-<faults>-<txsize>.txt`` per group (a TPS/latency
        grid plus a peak-TPS table in the §6 baseline-table column
        order) and machine-readable ``plots/matrix.json`` covering all
        groups.  No result files -> no artifacts, silently (a fresh
        checkout has nothing to matrix)."""
        groups = self.matrix()
        if not groups:
            return
        os.makedirs(PathMaker.plot_path(), exist_ok=True)
        as_json = {}
        for (faults, tx_size), group in sorted(groups.items()):
            nodes, rates, cells = \
                group["nodes"], group["rates"], group["cells"]
            lines = [
                "-----------------------------------------",
                " MATRIX (end-to-end TPS / latency ms):",
                "-----------------------------------------",
                f" Faults: {faults}",
                f" Transaction size: {tx_size} B",
                "",
            ]
            header = " nodes\\rate |" + "".join(
                f" {r:>14,} |" for r in rates)
            lines += [header, " " + "-" * (len(header) - 1)]
            for n in nodes:
                row = f" {n:>10} |"
                for r in rates:
                    cell = cells.get((n, r))
                    if cell is None:
                        row += f" {'-':>14} |"
                        continue
                    text = f"{cell['tps']:,}/{cell['latency_ms']:,}"
                    if cell.get("chaos"):
                        c = cell["chaos"]
                        text += " C" if not c["slo_fail"] else " C!"
                    elif cell.get("chaos_run"):
                        text += " +C"
                    row += f" {text:>14} |"
                lines.append(row)
            lines += [
                "",
                " C = chaos/WAN run (SLO pass), C! = SLO breach,"
                " +C = separate chaos run of this cell (see matrix.json)",
                "",
                " Peak end-to-end TPS per committee size"
                " (the SURVEY §6 baseline-table shape):",
                " | Nodes | Faults | Input rate | Peak e2e TPS |"
                " e2e latency | Chaos |",
                " |---|---|---|---|---|---|",
            ]
            for n in nodes:
                best = None
                for r in rates:
                    cell = cells.get((n, r))
                    if cell and (best is None
                                 or cell["tps"] > best[1]["tps"]):
                        best = (r, cell)
                if best is None:
                    continue
                r, cell = best
                c = cell.get("chaos")
                chaos_col = "-" if not c else (
                    f"{c['slo_pass']} SLO pass"
                    + (f", {c['slo_fail']} FAIL" if c["slo_fail"] else "")
                    + (f"; {c['wan']}" if c.get("wan") else ""))
                lines.append(
                    f" | {n} | {faults} | {r:,} | {cell['tps']:,} |"
                    f" {cell['latency_ms']:,} ms | {chaos_col} |")
            filename = join(PathMaker.plot_path(),
                            f"matrix-{faults}-{tx_size}.txt")
            with open(filename, "w") as f:
                f.write("\n".join(lines) + "\n")
            as_json[f"{faults}-{tx_size}"] = {
                "faults": faults, "tx_size": tx_size,
                "nodes": nodes, "rates": rates,
                "cells": {f"{n}-{r}": cell
                          for (n, r), cell in sorted(cells.items())},
            }
        with open(join(PathMaker.plot_path(), "matrix.json"), "w") as f:
            json.dump(as_json, f, indent=1, sort_keys=True)

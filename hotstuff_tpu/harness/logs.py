"""Log mining → TPS/BPS/latency metrics.

Reimplements the reference's measurement pipeline
(benchmark/benchmark/logs.py:17-251): client logs give input rate, start
time and per-sample send times; node logs give proposal/commit times per
batch digest, batch sizes, and sample-tx→batch joins. Consensus metrics
count from first proposal to last commit; end-to-end metrics count from
client start. The log grammar is frozen — the C++ node emits exactly these
phrasings (see native/src/*/: "NOTE: ... used to compute performance").
"""

from __future__ import annotations

import re
from datetime import datetime
from glob import glob
from os.path import join
from re import findall, search
from statistics import mean

from .utils import Print

SIGNATURE_LENGTH = 0
PUBLICKEY_LENGTH = 0

# A well-formed line of the frozen log grammar (common/log.hpp):
# "[<RFC3339 ms>Z <LEVEL> <module>] <message>".  Concurrent writers to
# one fd (a chaos-restarted node appending to its old log, the C++
# node's multiple threads under memory pressure) can interleave or tear
# lines; anything that does not match this prefix is dropped and
# counted BEFORE the regex mining, so a torn fragment can neither fake
# a fatal " ERROR " hit nor crash a config search().
_WELL_FORMED_LINE = re.compile(
    r"^\[\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z "
    r"(?:ERROR|WARN|INFO|DEBUG) [\w:.\-]+\] ")


class ParseError(Exception):
    pass


class LogParser:
    def __init__(self, clients, nodes, faults, chaos_events=None,
                 strict_chaos=False, twins=None, wan=None, slos=None,
                 strict_lines=False):
        inputs = [clients, nodes]
        assert all(isinstance(x, list) for x in inputs)
        assert all(isinstance(x, str) for y in inputs for x in y)
        if not clients or not nodes:
            raise ParseError("missing client or node logs")

        # Torn-line tolerance: sanitize every log up front (skip-and-
        # count).  Non-strict mode — the default — NEVER raises on a
        # malformed line; the count is surfaced as a parser note so a
        # torn-log run is visible, not silent.  strict_lines is for
        # tests that want to assert a log grammar regression loudly.
        self.malformed_lines = 0
        clients = [self._sanitize_log(x) for x in clients]
        nodes = [self._sanitize_log(x) for x in nodes]
        twins = [self._sanitize_log(x) for x in (twins or [])]
        if strict_lines and self.malformed_lines:
            raise ParseError(
                f"{self.malformed_lines} malformed log line(s) "
                "(strict_lines mode)")

        self.faults = faults
        # graftwan: the WAN spec snapshot the run was shaped under and
        # the SLO table chaos recovery is judged against (None = default
        # table; both ride in from logs/*.json via process()).
        self.wan = wan
        self.slos = slos
        # graftchaos: executed fault events (PlanRunner.events shape).
        # Scripted faults change what counts as a client failure — a
        # client pinned to a replica the plan killed dies with it, which
        # is the fault model working, not a broken bench.  The tolerance
        # is scoped tightly: only as many client deaths as the plan has
        # DISTINCT killed/paused replicas; any further failure is a real
        # bug and still fatal.
        self.chaos_events = chaos_events
        self.chaos = None
        # Strictness rides with chaos mode: a scripted run (incl. surge
        # overload scenarios) must satisfy the recovery/fairness
        # assertions; a plain bench is merely described.
        self._strict_chaos = bool(strict_chaos)
        from ..chaos.plan import cascade_k

        self._tolerable_client_deaths = len({
            e.get("target") for e in (chaos_events or ())
            if e.get("action") in ("kill", "pause")
            and str(e.get("target", "")).startswith("node:")
        }) + sum(
            # graftview: a leader-cascade kills up to k replicas chosen
            # at runtime — their clients die with them, which is the
            # fault model working (same scoped tolerance as node kills).
            cascade_k(e.get("params")) for e in (chaos_events or ())
            if e.get("target") == "leader-cascade")
        # Free-form annotations appended to the CONFIG section of the
        # summary (e.g. the harness marking a degraded host-crypto run,
        # or the sidecar's verifysched telemetry).  Extra lines are
        # invisible to the frozen result-grammar parsers, which match
        # labelled fields only.
        self.notes = []
        # grafttrace: the critical-path summary (note_trace) and the
        # sampled metrics time series (note_metrics) land here for
        # bench.py's machine-readable round trip.  graftscope adds the
        # per-replica node series accounting (hosts + divergence).
        self.trace = None
        self.metrics = None
        self.node_metrics = None
        # graftcadence: the OP_STATS ``cadence`` section (ring tick
        # rate, occupancy, pad-fill, generation drops, queue waits)
        # lands here machine-readable for bench.py's round trip.
        self.cadence = None
        # graftingress: the OP_STATS ``ingress`` bulk-lane feed mix
        # (ingress-fed vs offchain-fed), machine-readable for bench.py.
        self.sidecar_ingress = None
        # graftfleet: cross-tenant verdict-cache dedup, the per-tenant
        # scheduler section, the node-side failover evidence, and the
        # greedy-flood verdict — all machine-readable for bench.py.
        self.sidecar_dedup = None
        self.sidecar_tenants = None
        self.failover = None
        self.tenant_flood = None
        if self.malformed_lines:
            self.notes.append(
                f"Parser: skipped {self.malformed_lines} torn/malformed "
                "log line(s) (concurrent writers)")
        if isinstance(faults, int):
            self.committee_size = len(nodes) + int(faults)
        else:
            self.committee_size = "?"

        try:
            results = [self._parse_client(x) for x in clients]
        except (ValueError, IndexError, AttributeError) as e:
            raise ParseError(f"Failed to parse client logs: {e}")
        self.size, self.rate, self.start, misses, self.sent_samples, \
            client_ingress = zip(*results)
        self.misses = sum(misses)

        try:
            results = [self._parse_node(x) for x in nodes]
        except (ValueError, IndexError, AttributeError) as e:
            raise ParseError(f"Failed to parse node logs: {e}")
        proposals, commits, sizes, self.received_samples, timeouts, \
            configs, views, viewchanges, node_ingress = zip(*results)
        self.proposals = self._merge_earliest(proposals)
        self.commits = self._merge_earliest(commits)
        self.sizes = {
            k: v for x in sizes for k, v in x.items() if k in self.commits
        }
        self.timeouts = max(timeouts)
        self.configs = configs
        # graftview: aggregated view-change evidence — TCs formed (by
        # round, so every replica completing the same quorum counts
        # once), TC-driven round transitions with the largest jump, and
        # the robustness counters (ejected bad signers, dropped
        # future-round floods).  Machine-readable on self.viewchange;
        # the note makes a storm-surviving run read as exactly that.
        self.viewchange = self._aggregate_viewchange(viewchanges)
        vc = self.viewchange
        if vc["tc_rounds"] or vc["transitions"]:
            rounds = ", ".join(str(r) for r in vc["tc_rounds"][:8])
            if len(vc["tc_rounds"]) > 8:
                rounds += ", ..."
            formed = f"TC formed for {len(vc['tc_rounds'])} round(s)"
            if rounds:
                formed += f" ({rounds})"
            self.notes.append(
                f"View change: {formed}; {vc['transitions']} TC round "
                f"transition(s), max jump {vc['max_jump']} round(s)")
        if vc["ejected"]:
            self.notes.append(
                f"View change: {vc['ejected']} invalid timeout "
                "signer(s) ejected by batched TC verify")
        if vc["dropped_future"]:
            self.notes.append(
                f"View change: {vc['dropped_future']} future-round "
                "timeout(s) dropped beyond the aggregation horizon")

        # Twins: logs of equivocating replicas (same key as an honest
        # node, own ports).  Parsed ONLY for their commit views — an
        # adversarial replica's metrics/errors are its own business —
        # and folded into the safety assertion below: their commits must
        # agree with (or be behind) the honest committee's, never fork
        # it.  Twin commits stay OUT of self.commits: a shadow replica
        # must not move throughput/latency numbers.
        self.twins = list(twins or [])
        self._commit_views = list(views) + \
            [self._parse_commit_view(log) for log in self.twins]
        self._check_safety()
        if self.twins:
            self.notes.append(
                f"Twins: {len(self.twins)} equivocating replica(s) "
                "active; safety held (no conflicting commits)")

        if self.misses != 0:
            Print.warn(
                f"Clients missed their target rate {self.misses:,} time(s)")
        # Nodes are expected to time out once at the beginning at most;
        # scripted faults legitimately add a view change per event, so a
        # chaos plan raises the allowance by its event count rather than
        # silencing the check.
        if self.timeouts > 2 + len(self.chaos_events or ()):
            Print.warn(f"Nodes timed out {self.timeouts:,} time(s)")

        # Sidecar circuit-breaker transitions (native/crypto/sidecar_client
        # logs them at WARN/INFO): surfaced as CONFIG notes so a run that
        # silently spent its window on host verify is visible in the
        # summary.
        opens = sum(len(findall(r"circuit breaker OPEN", log))
                    for log in nodes)
        closes = sum(len(findall(r"circuit breaker CLOSED", log))
                     for log in nodes)
        if opens or closes:
            self.notes.append(
                f"Sidecar circuit breaker: {opens} open / "
                f"{closes} re-attach transition(s)")

        # graftfleet failover evidence (native/crypto/sidecar_client
        # fleet ladder): sticky-endpoint re-homes, in-flight resubmits,
        # and the protocol-v6 HELLO accepts per endpoint.  Surfaced so a
        # run that survived a fleet-member kill reads as exactly that;
        # machine-readable on self.failover for the strict drill check
        # in note_chaos_events and bench.py's round trip.
        rehomes = sum(len(findall(
            r"sidecar failover: endpoint \d+ unhealthy, "
            r"re-homed to endpoint \d+", log)) for log in nodes)
        resubmits = sum(len(findall(
            r"sidecar failover: endpoint \d+ failed in flight, "
            r"resubmitting to endpoint \d+", log)) for log in nodes)
        hellos = [(int(ix), tenant) for log in nodes for ix, tenant in
                  findall(r"HELLO accepted by endpoint (\d+): "
                          r"tenant (\S+) \(protocol v\d+\)", log)]
        if rehomes or resubmits or hellos:
            self.failover = {
                "rehomes": rehomes,
                "resubmits": resubmits,
                "hello_accepts": len(hellos),
                "endpoints": sorted({ix for ix, _ in hellos}),
                "tenants": sorted({t for _, t in hellos}),
            }
            parts = [f"{rehomes} re-home(s)", f"{resubmits} in-flight "
                     "resubmit(s)"]
            if hellos:
                parts.append(
                    f"{len(hellos)} HELLO accept(s) across endpoint(s) "
                    + ", ".join(str(i) for i in self.failover["endpoints"])
                    + " (tenant "
                    + ", ".join(self.failover["tenants"]) + ")")
            self.notes.append("Sidecar fleet: " + "; ".join(parts))

        # graftsurge overload evidence: the node's bounded ingress logs
        # watermark crossings, and clients log (rate-limited) BUSY
        # backoffs.  Surfaced so an overloaded-but-surviving run reads
        # as exactly that, not as a quiet healthy one.
        pauses = sum(len(findall(r"Ingress paused", log)) for log in nodes)
        resumes = sum(len(findall(r"Ingress resumed", log))
                      for log in nodes)
        busy_lines = sum(len(findall(r"Node busy \(retry-after", log))
                         for log in clients)
        if pauses or resumes or busy_lines:
            self.notes.append(
                f"Ingress backpressure: {pauses} receiver pause(s) / "
                f"{resumes} resume(s); clients logged {busy_lines} busy "
                "backoff line(s)")

        # graftingress: signed-ingress accounting + the two assertions
        # that make a forgery-mix run meaningful — ALWAYS strict, chaos
        # plan or not: (a) zero forged txs may reach a sealed batch on a
        # verify-ingress run; (b) multi-process client shards must share
        # the offered load fairly (open-loop shards at equal rates that
        # diverge wildly mean a shard starved or died silently).
        self.ingress = self._aggregate_ingress(client_ingress,
                                               node_ingress)
        ing = self.ingress
        if ing["verify_on"] and ing["forged_committed"]:
            raise ParseError(
                f"{ing['forged_committed']} forged transaction(s) "
                "reached a sealed batch on a verify-ingress run — the "
                "admission-verify stage admitted a forgery")
        if ing["shards"] >= 2:
            sent = ing["shard_sent"]
            if sent and min(sent) < 0.25 * max(sent):
                raise ParseError(
                    "client shard fairness violated: per-shard sent "
                    f"totals {sent} diverge beyond 4x (a shard starved "
                    "or died silently)")
            self.notes.append(
                f"Client shards: {ing['shards']} process(es), sent "
                + ", ".join(f"{s:,}" for s in sent) + " tx")
        if ing["signed"]:
            self.notes.append(
                f"Signed ingress: {ing['verified']:,} tx admission-"
                f"verified; clients sent {ing['forged_sent']:,}+ forged "
                f"({ing['forge_pct']:g}% mix), nodes rejected "
                f"{ing['forged_rejected']:,} at admission, "
                f"{ing['busy_shed']:,} shed busy, "
                f"{ing['forged_committed']} committed")

        if self.wan is not None:
            self.note_wan(self.wan)
        if self.chaos_events is not None:
            self.note_chaos_events(self.chaos_events, strict=strict_chaos,
                                   slos=self.slos)

    # -- parsing -------------------------------------------------------------

    def _sanitize_log(self, log: str) -> str:
        """Drop (and count) lines outside the frozen log grammar.  The
        regex miners below would mostly skip garbage anyway; the fatal
        checks (`` ERROR ``, ``panic``) and the labelled config
        ``search()``es are what a torn fragment could corrupt.  C++
        runtime-abort output (libstdc++'s ``terminate called ...``) is
        printed with NO log prefix, so it is explicitly kept — dropping
        it would let ``_parse_node``'s crash check parse a dead replica
        as a clean run."""
        good = []
        for line in log.splitlines():
            if not line.strip():
                continue
            if _WELL_FORMED_LINE.match(line) or \
                    search(r"terminate called|panic", line) is not None:
                good.append(line)
            else:
                self.malformed_lines += 1
        return "\n".join(good) + ("\n" if good else "")

    @staticmethod
    def _merge_earliest(dicts):
        merged = {}
        for d in dicts:
            for k, v in d.items():
                if k not in merged or merged[k] > v:
                    merged[k] = v
        return merged

    @staticmethod
    def _to_posix(ts):
        return datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()

    def _parse_client(self, log):
        # Fatal client conditions in the C++ grammar: any ERROR-level line,
        # or the send-failure WARN that precedes client exit
        # (native/src/node/client.cpp).  Under a chaos plan a client
        # pinned to a murdered/paused replica dies WITH its replica —
        # that is the fault model, not a broken bench — so the failure is
        # tolerated and noted instead (the committee metrics come from
        # the surviving logs).
        if search(r" ERROR ", log) is not None or \
                search(r"Failed to send transaction", log) is not None:
            if self._tolerable_client_deaths <= 0:
                raise ParseError("Client(s) failed")
            self._tolerable_client_deaths -= 1
            self.notes.append(
                "Chaos: a client died with its faulted replica "
                "(send failure tolerated under the fault plan)")

        size = int(search(r"Transactions size: (\d+)", log).group(1))
        rate = int(search(r"Transactions rate: (\d+)", log).group(1))
        start = self._to_posix(search(r"\[(.*Z) .* Start ", log).group(1))
        misses = len(findall(r"rate too high", log))
        samples = {
            int(s): self._to_posix(t)
            for t, s in findall(r"\[(.*Z) .* sample transaction (\d+)", log)
        }
        # graftingress accounting: all OPTIONAL (legacy unsigned logs
        # parse exactly as before).  The forged/sent counters are
        # cumulative in the log lines, so the per-log total is the max.
        m = search(r"Signed ingress enabled \(seed \d+, forge ([0-9.]+)%, "
                   r"user offset (\d+), sample offset (\d+)\)", log)
        ingress = {
            "signed": m is not None,
            "forge_pct": float(m.group(1)) if m else 0.0,
            "user_offset": int(m.group(2)) if m else 0,
            "sample_offset": int(m.group(3)) if m else 0,
            "forged_sent": max(
                (int(n) for n in findall(
                    r"Forged transaction sent \((\d+) total\)", log)),
                default=0),
            "sent": max(
                (int(n) for n in findall(
                    r"Sent (\d+) transactions", log)),
                default=0),
        }
        return size, rate, start, misses, samples, ingress

    def _parse_node(self, log):
        # Fatal node conditions: ERROR-level lines (uncaught exceptions,
        # bind failures, store corruption — native/src/node/main.cpp) or a
        # C++ runtime abort message.
        if search(r" ERROR ", log) is not None or \
                search(r"terminate called|panic", log) is not None:
            raise ParseError("Node(s) failed")

        # Earliest occurrence wins even within one log (a digest can be
        # re-proposed after a fallthrough round).
        proposals = {}
        for t, d in findall(r"\[(.*Z) .* Created B\d+ -> ([^ ]+=)", log):
            ts = self._to_posix(t)
            if d not in proposals or proposals[d] > ts:
                proposals[d] = ts
        commits = {}
        for t, d in findall(r"\[(.*Z) .* Committed B\d+ -> ([^ ]+=)", log):
            ts = self._to_posix(t)
            if d not in commits or commits[d] > ts:
                commits[d] = ts
        sizes = {
            d: int(s)
            for d, s in findall(r"Batch ([^ ]+) contains (\d+) B", log)
        }
        samples = {
            int(s): d
            for d, s in findall(r"Batch ([^ ]+) contains sample tx (\d+)",
                                log)
        }
        timeouts = len(findall(r".* WARN .* Timeout reached", log))

        # graftview evidence in the frozen log grammar (core.cpp
        # finish_tc/handle_tc/resolve_tc_batch/handle_timeout; "change
        # both sides together").  "Dropped N ..." lines carry CUMULATIVE
        # counts, so the per-log total is the max, not the sum.
        viewchange = {
            "tcs": [(int(r), int(n)) for r, n in findall(
                r"Formed TC for round (\d+) \((\d+) timeouts", log)],
            "jumps": [(int(a), int(b)) for a, b in findall(
                r"View change: round (\d+) -> (\d+) via TC", log)],
            "ejected": sum(int(n) for n in findall(
                r"Ejected (\d+) invalid timeout signer", log)),
            "dropped_future": max(
                (int(n) for n in findall(
                    r"Dropped (\d+) future-round timeout", log)),
                default=0),
        }

        configs = {
            "consensus": {
                "timeout_delay": int(
                    search(r"Timeout delay .* (\d+)", log).group(1)),
                "sync_retry_delay": int(
                    search(r"consensus.* Sync retry delay .* (\d+)",
                           log).group(1)),
            },
            "mempool": {
                "gc_depth": int(
                    search(r"Garbage collection .* (\d+)", log).group(1)),
                "sync_retry_delay": int(
                    search(r"mempool.* Sync retry delay .* (\d+)",
                           log).group(1)),
                "sync_retry_nodes": int(
                    search(r"Sync retry nodes .* (\d+)", log).group(1)),
                "batch_size": int(
                    search(r"Batch size .* (\d+)", log).group(1)),
                "max_batch_delay": int(
                    search(r"Max batch delay .* (\d+)", log).group(1)),
            },
        }
        # graftview pacemaker knobs: OPTIONAL (logs predating the
        # backoff pacemaker stay parseable) — present only when the node
        # logged them.
        for key, pattern in (
                ("timeout_backoff_factor_pct",
                 r"Timeout backoff factor set to (\d+)"),
                ("timeout_backoff_cap",
                 r"Timeout backoff cap set to (\d+)"),
                ("timeout_jitter_pct", r"Timeout jitter set to (\d+)"),
                ("timeout_future_horizon",
                 r"Timeout future horizon set to (\d+)")):
            m = search(pattern, log)
            if m:
                configs["consensus"][key] = int(m.group(1))
        # graftingress: admission-verify evidence, all OPTIONAL (logs
        # from unsigned runs parse exactly as before).  Rejection totals
        # are cumulative in the WARN line, so max per log; verified
        # totals ride the METRICS suffix (max per log, trace runs only).
        m = search(r"Ingress signature verification enabled with batch "
                   r"(\d+)", log)
        if m:
            configs["mempool"]["verify_batch"] = int(m.group(1))
        ingress = {
            "verify_on": m is not None,
            "forged_committed": len(findall(r"contains forged tx", log)),
            "forged_rejected": max(
                (int(n) for n in findall(
                    r"forged transaction\(s\) at ingress admission "
                    r"\((\d+) total\)", log)),
                default=0),
            "verified": max(
                (int(n) for n in findall(r"METRICS .* verified=(\d+)",
                                         log)),
                default=0),
            "busy_shed": max(
                (int(n) for n in findall(
                    r"Admission verify busy; shed .* \((\d+) total\)",
                    log)),
                default=0),
        }
        return proposals, commits, sizes, samples, timeouts, configs, \
            self._parse_commit_view(log), viewchange, ingress

    @staticmethod
    def _parse_commit_view(log):
        """``{height: {digests committed at that height}}`` for one log —
        the per-replica commit view the safety assertion compares.
        Lenient by design (no error/config checks): it also parses the
        logs of Twins replicas, whose own health is irrelevant."""
        view = {}
        for h, d in findall(r"Committed B(\d+) -> ([^ ]+=)", log):
            view.setdefault(int(h), set()).add(d)
        return view

    def _check_safety(self):
        """STRICT safety assertion: no two logs may commit conflicting
        blocks at the same height.  Every pair of commit views (honest
        nodes AND twins) is compared per height: the digest sets must be
        equal — or one a subset of the other, which teardown killing a
        node mid-write legitimately produces.  (A digest appearing at
        two DIFFERENT heights is payload duplication from re-proposal,
        not a fork, and stays out of this check.)

        Equivocation (Twins) must be CONTAINED — absorbed into one
        agreed chain — not merely survived; any violation is a hard
        ParseError, chaos plan or not."""
        by_height = {}
        for li, view in enumerate(self._commit_views):
            for h, digests in view.items():
                by_height.setdefault(h, []).append((li, digests))
        violations = []
        for h, entries in sorted(by_height.items()):
            for i in range(len(entries)):
                for j in range(i + 1, len(entries)):
                    a, b = entries[i][1], entries[j][1]
                    if not (a <= b or b <= a):
                        violations.append(
                            f"height {h}: log {entries[i][0]} committed "
                            f"{sorted(x[:12] + '...' for x in a - b)} but "
                            f"log {entries[j][0]} committed "
                            f"{sorted(x[:12] + '...' for x in b - a)}")
        if violations:
            raise ParseError(
                "SAFETY VIOLATION — conflicting commits: "
                + "; ".join(violations[:5]))

    @staticmethod
    def _aggregate_viewchange(viewchanges) -> dict:
        """Committee-wide view-change summary from the per-log mining:
        TC rounds deduped (every replica completing the same quorum
        logs its own "Formed TC"), transitions counted raw (each
        replica pays its own round jump), ejections summed, cumulative
        future-drop counters summed across replicas."""
        tc_rounds = sorted({r for vc in viewchanges for r, _ in vc["tcs"]})
        jumps = [b - a for vc in viewchanges for a, b in vc["jumps"]]
        return {
            "tc_rounds": tc_rounds,
            "tcs_formed": sum(len(vc["tcs"]) for vc in viewchanges),
            "transitions": len(jumps),
            "max_jump": max(jumps, default=0),
            "ejected": sum(vc["ejected"] for vc in viewchanges),
            "dropped_future": sum(
                vc["dropped_future"] for vc in viewchanges),
        }

    @staticmethod
    def _aggregate_ingress(client_ingress, node_ingress) -> dict:
        """Run-wide signed-ingress summary from the per-log mining.
        Client forged/sent counters are cumulative per log (already
        max-reduced), so the run totals are sums; shard mode is
        detected by >= 2 clients carrying disjoint sample-id offsets.
        ``forged_sent`` undercounts by at most one forge-log interval
        per client (the line is rate-limited)."""
        shard_clients = [c for c in client_ingress
                         if c["signed"] or c["sample_offset"]]
        offsets = {c["sample_offset"] for c in shard_clients}
        shards = len(shard_clients) if len(offsets) >= 2 else 0
        return {
            "signed": any(c["signed"] for c in client_ingress),
            "verify_on": any(n["verify_on"] for n in node_ingress),
            "forge_pct": max(
                (c["forge_pct"] for c in client_ingress), default=0.0),
            "forged_sent": sum(c["forged_sent"] for c in client_ingress),
            "sent": sum(c["sent"] for c in client_ingress),
            "shards": shards,
            "shard_sent": [c["sent"] for c in shard_clients]
            if shards else [],
            "verified": sum(n["verified"] for n in node_ingress),
            "forged_rejected": sum(
                n["forged_rejected"] for n in node_ingress),
            "busy_shed": sum(n["busy_shed"] for n in node_ingress),
            "forged_committed": sum(
                n["forged_committed"] for n in node_ingress),
        }

    # -- metrics -------------------------------------------------------------

    def _tx_bytes(self):
        return self.size[0] + PUBLICKEY_LENGTH + SIGNATURE_LENGTH

    def _window_tps(self, t0: float, t1: float) -> float:
        """Committed tx/s over the wall-clock window [t0, t1)."""
        if t1 <= t0:
            return 0.0
        byte_total = sum(self.sizes.get(d, 0)
                         for d, c in self.commits.items()
                         if t0 <= c < t1)
        return byte_total / self._tx_bytes() / (t1 - t0)

    def _consensus_throughput(self):
        if not self.commits:
            return 0, 0, 0
        start = min(self.proposals.values())
        end = max(self.commits.values())
        duration = end - start
        byte_total = sum(self.sizes.values())
        bps = byte_total / duration if duration else 0
        tps = bps / self._tx_bytes()
        return tps, bps, duration

    def _consensus_latency(self):
        latency = [
            c - self.proposals[d]
            for d, c in self.commits.items()
            if d in self.proposals
        ]
        return mean(latency) if latency else 0

    def _end_to_end_throughput(self):
        if not self.commits:
            return 0, 0, 0
        start = min(self.start)
        end = max(self.commits.values())
        duration = end - start
        byte_total = sum(self.sizes.values())
        bps = byte_total / duration if duration else 0
        tps = bps / self._tx_bytes()
        return tps, bps, duration

    def _end_to_end_latency(self):
        latency = []
        for sent, received in zip(self.sent_samples, self.received_samples):
            for tx_id, batch_id in received.items():
                if batch_id in self.commits and tx_id in sent:
                    latency.append(self.commits[batch_id] - sent[tx_id])
        return mean(latency) if latency else 0

    def result(self):
        consensus_latency = self._consensus_latency() * 1000
        consensus_tps, consensus_bps, _ = self._consensus_throughput()
        end_to_end_tps, end_to_end_bps, duration = \
            self._end_to_end_throughput()
        end_to_end_latency = self._end_to_end_latency() * 1000
        cfg = self.configs[0]
        batch_size = cfg["mempool"]["batch_size"]
        tx_bytes = self._tx_bytes()
        mean_block = (
            round(mean(self.sizes.values()) / tx_bytes, 2)
            if self.sizes else 0)
        return (
            "\n"
            "-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f" Faults: {self.faults} nodes\n"
            f" Committee size: {self.committee_size} nodes\n"
            f" Input rate: {sum(self.rate):,} tx/s\n"
            f" Transaction size: {self.size[0]:,} B\n"
            f" Execution time: {round(duration):,} s\n"
            "\n"
            f" Consensus timeout delay: "
            f"{cfg['consensus']['timeout_delay']:,} ms\n"
            f" Consensus sync retry delay: "
            f"{cfg['consensus']['sync_retry_delay']:,} ms\n"
            f" Mempool GC depth: {cfg['mempool']['gc_depth']:,} rounds\n"
            f" Mempool sync retry delay: "
            f"{cfg['mempool']['sync_retry_delay']:,} ms\n"
            f" Mempool sync retry nodes: "
            f"{cfg['mempool']['sync_retry_nodes']:,} nodes\n"
            f" Mempool batch size: {batch_size:,} B\n"
            f" Mempool max batch delay: "
            f"{cfg['mempool']['max_batch_delay']:,} ms\n"
            + "".join(f" {note}\n" for note in self.notes) +
            "\n"
            " + RESULTS:\n"
            f" Consensus TPS: {round(consensus_tps):,} tx/s\n"
            f" Consensus BPS: {round(consensus_bps):,} B/s\n"
            f" Consensus latency: {round(consensus_latency):,} ms\n"
            "\n"
            f" End-to-end TPS: {round(end_to_end_tps):,} tx/s\n"
            f" End-to-end BPS: {round(end_to_end_bps):,} B/s\n"
            f" End-to-end latency: {round(end_to_end_latency):,} ms\n"
            "\n"
            f" Max transactions per block: "
            f"{round(batch_size / tx_bytes)} tx/block\n"
            f" Actual transactions per block: {mean_block} tx/block\n"
            f" Blocks per second: "
            f"{round(len(self.sizes) / duration) if duration > 0 else 0} "
            "blocks/s\n"
            "-----------------------------------------\n"
        )

    def note_sidecar_stats(self, stats: dict):
        """Fold a verifysched OP_STATS snapshot (sidecar/sched/stats.py
        schema) into the summary's CONFIG notes — label-free lines, so
        the frozen result grammar never sees them.  Telemetry is
        best-effort: a snapshot with hostile value types (a
        version-skewed sidecar, a writer cut off mid-dump) adds no
        notes at all rather than raising or leaving a partial block."""
        if not isinstance(stats, dict) or not stats.get("launches"):
            return
        # Strict fairness (graftsurge) FIRST, before any cosmetic note
        # formatting: under a scripted run, shedding a latency-class
        # (consensus) request while bulk slipped past the
        # bulk-before-latency gate is a policy regression, not weather —
        # and the assertion must not depend on sibling telemetry keys
        # formatting cleanly.
        surge = stats.get("surge")
        if self._strict_chaos and isinstance(surge, dict):
            violations = surge.get("fairness_violations")
            if isinstance(violations, (int, float)) and violations:
                raise ParseError(
                    f"surge fairness violated: {violations:g} bulk "
                    "request(s) admitted while the latency class was "
                    "shedding (bulk-before-latency)")
            # graftfleet: the DRR rotation's strict invariant — a
            # backlogged tenant passed over a full quantum rotation is
            # a scheduler bug, never weather.
            starvation = surge.get("tenant_starvation")
            if isinstance(starvation, (int, float)) and starvation:
                raise ParseError(
                    f"tenant fairness violated: {starvation:g} tenant "
                    "starvation event(s) (a backlogged tenant was "
                    "passed over a full DRR rotation)")
        lines = []
        # graftfleet: a per-endpoint snapshot (sidecar-stats-<i>.json)
        # prefixes its lines so a fleet teardown reads per member.
        endpoint = stats.get("_endpoint")
        # grafttrace fallback marker: the harness could not reach the
        # sidecar at teardown (chaos-killed before the final fetch) and
        # substituted the periodic sampler's last good snapshot — say
        # so, instead of letting sampled numbers masquerade as final.
        sampled_at = stats.get("_from_sample_at")
        if isinstance(sampled_at, (int, float)):
            ts = datetime.utcfromtimestamp(sampled_at).strftime(
                "%Y-%m-%dT%H:%M:%SZ")
            lines.append(f"Sidecar stats from last sample @ {ts} "
                         "(sidecar unreachable at teardown)")
        try:
            by_class = stats.get("launches_by_class", {})
            lines.append(
                f"Sidecar launches: {stats['launches']:,} "
                f"(latency {by_class.get('latency', 0):,}, "
                f"bulk {by_class.get('bulk', 0):,})")
            paths = stats.get("paths", {})
            if paths:
                lines.append("Sidecar verify paths: " + ", ".join(
                    f"{k}={v:,}" for k, v in sorted(paths.items())))
            waits = stats.get("queue_wait", {})
            if waits:
                lines.append("Sidecar queue wait: " + ", ".join(
                    f"{cls} p50 {w.get('p50_ms', 0)} ms / "
                    f"p99 {w.get('p99_ms', 0)} ms"
                    for cls, w in sorted(waits.items()) if w.get("n")))
            lines.append(
                f"Sidecar pad fill: {stats.get('bulk_fill_sigs', 0):,} "
                f"sigs (waste {stats.get('pad_waste_sigs', 0):,})")
            mesh = stats.get("mesh", {})
            if mesh.get("sharded_launches"):
                hist = ", ".join(
                    f"{k}x{v:,}" for k, v in
                    sorted(mesh.get("shard_buckets", {}).items(),
                           key=lambda kv: int(kv[0])))
                lines.append(
                    f"Sidecar mesh launches: "
                    f"{mesh['sharded_launches']:,}"
                    + (f" (per-shard buckets {hist})" if hist else ""))
            # graftscale: bulk backlogs drained as ONE chunked
            # whole-backlog mesh scan, with the per-launch_cap ladder
            # dispatches the old path would have paid.
            scan = stats.get("scan", {})
            if scan.get("launches"):
                hist = ", ".join(
                    f"{k}x{v:,}" for k, v in
                    sorted(scan.get("chunk_hist", {}).items(),
                           key=lambda kv: int(kv[0])))
                lines.append(
                    f"Sidecar whole-backlog scans: "
                    f"{scan['launches']:,} "
                    f"({scan.get('sigs', 0):,} sigs"
                    + (f", chunks {hist}" if hist else "")
                    + f"), {scan.get('slices_avoided', 0):,} "
                    "slice(s) avoided")
            pipe = stats.get("pipeline", {})
            if pipe.get("pack_ms"):
                lines.append(
                    f"Sidecar pack overlap: "
                    f"{pipe.get('overlap_ratio', 0.0):.0%} of "
                    f"{pipe['pack_ms']:g} ms packing hidden behind "
                    "device execution")
            comp = stats.get("compile", {})
            if isinstance(comp, dict) and \
                    (comp.get("hits") or comp.get("misses")):
                boot = "warm boot" if comp.get("warm_boot") else "cold boot"
                lines.append(
                    f"Sidecar compile cache: {comp.get('hits', 0)} "
                    f"hit(s), {comp.get('misses', 0)} miss(es) — {boot}, "
                    f"warmup {comp.get('warmup_wall_s', 0):g} s"
                    + (f" (kernel {comp['kernel']})"
                       if comp.get("kernel") else ""))
            # graftguard: wedged launches, crash-only reboots, and the
            # quarantine lane — a run that survived a hung device leg
            # must read as exactly that, never as a quiet healthy one.
            g = stats.get("guard", {})
            if isinstance(g, dict) and (g.get("wedges")
                                        or g.get("reboots")
                                        or g.get("poisoned_records")):
                lines.append(
                    f"Sidecar guard: {g.get('wedges', 0):,} wedge(s), "
                    f"{g.get('reboots', 0):,} crash-only reboot(s) "
                    f"(canary {g.get('canary_passes', 0)} pass(es) / "
                    f"{g.get('canary_failures', 0)} fail(s), last reboot "
                    f"{g.get('last_reboot_wall_s', 0):g} s); "
                    f"{g.get('suspect_records', 0):,} quarantined / "
                    f"{g.get('poisoned_records', 0):,} poisoned "
                    f"record(s); {g.get('host_fallback_records', 0):,} "
                    f"host-fallback verdict(s), "
                    f"{g.get('busy_replies', 0):,} BUSY")
                if not g.get("device_ok", True):
                    lines.append(
                        "Sidecar guard: device leg DOWN at teardown "
                        "(host path serving; canary never passed)")
            full = stats.get("queue_full", {})
            if any(full.values()):
                lines.append("Sidecar queue-full sheds: " + ", ".join(
                    f"{k}={v:,}" for k, v in sorted(full.items())))
            # graftfleet: cross-tenant verdict-cache dedup — a record
            # fanned out by two tenants' replicas is device-verified
            # once; the hit rate is the headline the fleet bench cites.
            dd = stats.get("dedup")
            if isinstance(dd, dict) and (dd.get("cache_hits")
                                         or dd.get("inbatch_hits")
                                         or dd.get("misses")):
                self.sidecar_dedup = dd
                lines.append(
                    f"Sidecar dedup: {dd.get('cache_hits', 0):,} cache "
                    f"hit(s) + {dd.get('inbatch_hits', 0):,} in-batch, "
                    f"{dd.get('misses', 0):,} miss(es) "
                    f"(hit rate {dd.get('hit_rate', 0.0):.0%})")
            # graftfleet: the per-tenant scheduler section — noted only
            # when the run was actually multi-tenant, so single-tenant
            # (default-only) summaries stay byte-stable.
            tns = stats.get("tenants")
            if isinstance(tns, dict) and tns and (
                    len(tns) > 1 or set(tns) != {"default"}):
                self.sidecar_tenants = tns
                parts = []
                for tenant, rec in sorted(tns.items()):
                    admitted = sum((rec.get("admitted") or {}).values())
                    shed = sum((rec.get("shed") or {}).values())
                    parts.append(f"{tenant} admitted {admitted:,}"
                                 + (f" / shed {shed:,}" if shed else ""))
                lines.append(f"Sidecar tenants ({len(tns)}): "
                             + "; ".join(parts))
            surge = stats.get("surge")
            if isinstance(surge, dict):
                lines.extend(self._surge_lines(surge))
            # graftingress: bulk-lane feed mix — how much of the bulk
            # lane the mempool admission-verify stage actually drove.
            ing = stats.get("ingress")
            if isinstance(ing, dict) and (ing.get("bulk_requests")
                                          or ing.get("offchain_requests")):
                self.sidecar_ingress = ing
                total = ing.get("bulk_sigs", 0) + \
                    ing.get("offchain_sigs", 0)
                share = ing.get("bulk_sigs", 0) / total if total else 0.0
                lines.append(
                    f"Sidecar bulk lane: {ing.get('bulk_requests', 0):,} "
                    f"ingress-fed request(s) "
                    f"({ing.get('bulk_sigs', 0):,} sigs, {share:.0%} of "
                    f"bulk), {ing.get('offchain_requests', 0):,} "
                    f"offchain-fed "
                    f"({ing.get('offchain_sigs', 0):,} sigs)")
            # graftcadence: a run served by the resident ring says so —
            # tick rate, pad-fill and generation accounting in the
            # CONFIG notes, the full section machine-readable on
            # self.cadence for bench.py's round trip.
            cad = stats.get("cadence")
            if isinstance(cad, dict) and cad.get("ticks"):
                self.cadence = cad
                gen = cad.get("generation", {})
                wait = cad.get("queue_wait", {})
                pad = cad.get("pad_fill", {})
                lines.append(
                    f"Sidecar cadence ring: depth {cad.get('depth', 0)}"
                    f"{'' if cad.get('enabled') else ' (FELL BACK TO STAGED)'}"
                    f", {cad['ticks']:,} tick(s) @ "
                    f"{cad.get('tick_rate_hz', 0):g} Hz "
                    f"({cad.get('dispatch_ticks', 0):,} dispatching), "
                    f"pad fill {pad.get('ratio', 0.0):.0%}, "
                    f"{gen.get('drops', 0):,} generation drop(s) / "
                    f"{gen.get('expiries', 0):,} expiry(ies), "
                    f"queue wait p50 {wait.get('p50_ms', 0)} ms / "
                    f"p99 {wait.get('p99_ms', 0)} ms")
        except (TypeError, ValueError, AttributeError):
            return
        if isinstance(endpoint, str) and endpoint:
            lines = [f"[{endpoint}] {line}" for line in lines]
        self.notes.extend(lines)

    # graftfleet: the greedy-flood latency bound — the victim tenant's
    # latency-class queue-wait p99 may grow at most this factor across
    # the flood window before strict mode calls it an isolation failure.
    TENANT_FLOOD_WAIT_FACTOR = 2.0

    def note_tenant_flood(self, pre: dict, post: dict, victim: str,
                          strict: bool = False):
        """graftfleet greedy-tenant flood verdict: compare the victim
        tenant's latency-class queue-wait p99 between the pre-flood and
        post-flood OP_STATS snapshots, and hold the starvation
        invariant.  Strict mode (the scripted drill) raises ParseError
        when isolation failed; otherwise the verdict is a note.  The
        machine-readable verdict lands on ``self.tenant_flood``."""
        def _p99(stats):
            rec = (stats.get("tenants") or {}).get(victim) or {}
            wait = (rec.get("queue_wait") or {}).get("latency") or {}
            return wait.get("p99_ms"), wait.get("n", 0)

        try:
            starvation = (post.get("surge") or {}).get(
                "tenant_starvation", 0) or 0
            pre_p99, pre_n = _p99(pre)
            post_p99, post_n = _p99(post)
        except (TypeError, ValueError, AttributeError):
            return
        verdict = {"victim": victim, "starvation": starvation,
                   "pre_p99_ms": pre_p99, "post_p99_ms": post_p99,
                   "judged": bool(pre_n and post_n
                                  and isinstance(pre_p99, (int, float))
                                  and isinstance(post_p99, (int, float))
                                  and pre_p99 > 0),
                   "ok": True}
        if starvation:
            verdict["ok"] = False
            verdict["reason"] = (f"{starvation:g} tenant starvation "
                                 "event(s)")
        elif verdict["judged"] and \
                post_p99 > self.TENANT_FLOOD_WAIT_FACTOR * pre_p99:
            verdict["ok"] = False
            verdict["reason"] = (
                f"victim queue-wait p99 {post_p99:g} ms exceeds "
                f"{self.TENANT_FLOOD_WAIT_FACTOR:g}x pre-flood "
                f"{pre_p99:g} ms")
        self.tenant_flood = verdict
        if verdict["ok"]:
            bound = (f"p99 {post_p99:g} ms vs pre-flood {pre_p99:g} ms"
                     if verdict["judged"] else "not judged (no samples)")
            self.notes.append(
                f"Tenant flood: victim {victim} isolated ({bound}; "
                "0 starvation events)")
        else:
            self.notes.append(
                f"Tenant flood: isolation FAILED ({verdict['reason']})")
            if strict:
                raise ParseError(
                    "tenant isolation violated under greedy flood: "
                    + verdict["reason"])

    @staticmethod
    def _surge_lines(surge: dict) -> list:
        """CONFIG-note lines for the OP_STATS ``surge`` section."""
        lines = []
        shed = surge.get("shed", {})
        admitted = surge.get("admitted", {})
        if any(shed.values()) or any(admitted.values()):
            fair = "bulk-before-latency held" \
                if not surge.get("fairness_violations") else \
                f"{surge['fairness_violations']} fairness VIOLATION(S)"
            lines.append(
                "Sidecar surge: admitted "
                + ", ".join(f"{k}={v:,}"
                            for k, v in sorted(admitted.items()))
                + "; shed "
                + ", ".join(f"{k}={v:,}" for k, v in sorted(shed.items()))
                + f" ({fair})")
        if surge.get("tenant_starvation"):
            # Should never fire (strict mode already raised); the note
            # keeps a non-strict re-parse honest about it.
            lines.append(
                f"Sidecar tenant starvation: "
                f"{surge['tenant_starvation']:,} event(s) — DRR "
                "invariant VIOLATED")
        derate = surge.get("derate", {})
        if derate.get("engagements"):
            lines.append(
                f"Sidecar surge derate: engaged {derate['engagements']} "
                f"time(s), factor {derate.get('factor', 1.0)} "
                f"(recent overlap {derate.get('overlap_recent')})")
        return lines

    def note_trace(self, summary: dict):
        """Fold the grafttrace critical-path summary (obs/trace.py
        critical_path + sidecar_breakdown shape) into the CONFIG notes
        and onto ``self.trace`` for bench.py's headline round trip.
        Best-effort like every telemetry note: a hostile summary adds
        nothing rather than raising."""
        if not isinstance(summary, dict):
            return
        try:
            segs = summary.get("segments") or {}
            from ..obs.trace import DEVICE_SEGMENT, SEGMENTS, TOTAL_SEGMENT

            parts = []
            for name in SEGMENTS + (DEVICE_SEGMENT, TOTAL_SEGMENT):
                entry = segs.get(name)
                if entry and entry.get("n"):
                    parts.append(f"{name} p50 {entry['p50_ms']:g} ms / "
                                 f"p99 {entry['p99_ms']:g} ms")
            if not parts:
                return
            self.trace = summary
            # graftscope join accounting: device time nested inside
            # verify is only as good as the fraction of blocks it
            # covers — say the rate next to the percentiles.
            join = summary.get("join") or {}
            join_part = ""
            if isinstance(join.get("rate"), (int, float)):
                join_part = (f", sidecar join {join['rate']:.0%} of "
                             f"{join.get('with_verify', 0)} verify-traced")
            self.notes.append(
                f"Commit critical path ({summary.get('blocks', 0)} "
                f"block(s), {summary.get('complete', 0)} fully traced"
                f"{join_part}): " + "; ".join(parts))
            sc = summary.get("sidecar") or {}
            sc_parts = [f"{stage} p50 {e['p50_ms']:g} ms / "
                        f"p99 {e['p99_ms']:g} ms"
                        for stage, e in sorted(sc.items())
                        if e.get("n") and stage in ("queue", "pack",
                                                    "device")]
            if sc_parts:
                self.notes.append("Sidecar stage latency: "
                                  + "; ".join(sc_parts))
        except (TypeError, ValueError, AttributeError, KeyError):
            self.trace = None
            return

    def note_metrics(self, samples, malformed: int = 0):
        """Fold the sampled metrics time series (obs/sampler.py JSONL)
        into the summary: the in-window sample count as a CONFIG note,
        and — under a chaos plan — the per-event recovery curve, so an
        SLO verdict cites "telemetry resumed N ms after the fault"
        rather than a single post-fault commit scalar.

        graftscope: the series may mix sidecar OP_STATS samples with the
        C++ node's per-replica METRICS records; everything that reasons
        about the SIDECAR (its sample count, recovery curves, the
        baseline SLO judge) sees only the sidecar sub-series — a node
        tick must never read as sidecar telemetry resuming — while the
        node records feed the replica commit-rate notes."""
        if not samples:
            return
        from ..obs import split_samples

        sidecar, node = split_samples(samples)
        try:
            self.metrics = samples
            self._note_node_metrics(node)
            if not sidecar:
                return
            ok = [s for s in sidecar if s.get("ok")]
            window = max(s["t"] for s in sidecar) - \
                min(s["t"] for s in sidecar)
            note = (f"Sidecar metrics: {len(sidecar)} sample(s) "
                    f"({len(ok)} ok) over {window:g} s")
            if malformed:
                note += f", {malformed} torn line(s) skipped"
            self.notes.append(note)
            if not self.chaos:
                return
            from ..chaos.recovery import event_label
            from ..obs import recovery_curve

            for e in self.chaos.get("events", []):
                wall = e.get("wall")
                if not isinstance(wall, (int, float)):
                    continue
                curve = recovery_curve(sidecar, wall)
                e["telemetry"] = curve
                label = f"Chaos {event_label(e)}"
                if curve["resumed"]:
                    self.notes.append(
                        f"{label}: telemetry resumed "
                        f"{curve['resume_ms']:g} ms after event "
                        f"({curve['failed_ticks']} failed tick(s))")
                else:
                    self.notes.append(
                        f"{label}: telemetry did NOT resume "
                        f"({curve['failed_ticks']} failed tick(s) after "
                        "event)")
        except (TypeError, ValueError, AttributeError, KeyError):
            return
        self._judge_metrics_recovery(sidecar)

    # Straggler threshold: a replica sampling below this fraction of the
    # committee's median commit rate diverges (graftscope; evidence, not
    # failure — strict mode is unaffected).
    COMMIT_RATE_DIVERGENCE = 0.7

    def _note_node_metrics(self, node_samples):
        """Per-replica METRICS notes: series count plus the commit-rate
        divergence (straggler) evidence.  Best-effort like every
        telemetry note."""
        if not node_samples:
            return
        try:
            from ..obs import commit_rate_divergence

            hosts = sorted({s["node"] for s in node_samples})
            self.notes.append(
                f"Node metrics: {len(node_samples)} sample(s) across "
                f"{len(hosts)} replica(s)")
            div = commit_rate_divergence(
                node_samples, threshold=self.COMMIT_RATE_DIVERGENCE)
            self.node_metrics = {"hosts": hosts, "divergence": div}
            for s in div["stragglers"]:
                self.notes.append(
                    f"Replica commit-rate divergence: {s['host']} at "
                    f"{s['ratio']:.0%} of committee median "
                    f"({s['rate']:g} vs {div['median']:g} commits/s)")
        except (TypeError, ValueError, AttributeError, KeyError):
            return

    def _judge_metrics_recovery(self, samples):
        """Metrics-driven recovery-to-baseline verdicts (graftsurge /
        the PR 7 follow-up): the sampled throughput curve must RETURN to
        its pre-event baseline after every chaos event — the commit
        scalar proves liveness, this proves the system came back at
        strength.  Judged events that miss their class SLO fail the run
        under the strict chaos assertion; events without enough
        telemetry are surfaced as unjudged, never failed."""
        from ..chaos import judge_baseline_recovery

        if not self.chaos:
            return
        try:
            verdict = judge_baseline_recovery(
                samples, self.chaos.get("events", []), self.slos)
        except (TypeError, ValueError, KeyError, AttributeError):
            return
        self.chaos["slo_metrics"] = verdict
        for v in verdict["verdicts"]:
            label = f"Chaos SLO (baseline) {v['class']}"
            if not v["judged"]:
                self.notes.append(
                    f"{label}: not judged ({v.get('reason')})")
            elif v["ok"]:
                self.notes.append(
                    f"{label}: back to baseline in "
                    f"{v['recovered_ms']:g} ms PASS")
            else:
                self.notes.append(f"{label}: FAIL ({v.get('reason')})")
        if self._strict_chaos and not verdict["ok"]:
            raise ParseError(
                "metrics-driven recovery SLO breached: " + "; ".join(
                    f"{v['class']} ({v.get('reason')})"
                    for v in verdict["verdicts"] if not v["ok"]))

    def note_wan(self, wan: dict):
        """Fold the run's graftwan spec snapshot (logs/wan.json, the
        WanSpec.to_json shape) into the CONFIG notes so shaped numbers
        never masquerade as LAN numbers in the result files."""
        if not isinstance(wan, dict):
            return
        links = wan.get("links") or []
        parts = []
        for link in links:
            if not isinstance(link, dict):
                continue
            label = link.get("name") or \
                f"{link.get('src')}>{link.get('dst')}"
            shape = ", ".join(
                f"{k.split('_')[0]} {link[k]:g}"
                for k in ("latency_ms", "jitter_ms", "loss_pct",
                          "rate_mbit") if link.get(k))
            parts.append(f"{label} ({shape})" if shape else label)
        note = f"WAN: {len(links)} shaped link(s)"
        if parts:
            note += ": " + "; ".join(parts)
        if wan.get("default"):
            note += " + default shape"
        self.notes.append(note)

    def note_chaos_events(self, events, strict=False, slos=None):
        """Fold executed graftchaos events into the summary: per-fault
        recovery latency (first merged commit strictly after each event's
        wall stamp — hotstuff_tpu/chaos/recovery.py) as CONFIG notes,
        per-fault-class SLO verdicts (chaos/slo.py) as notes plus the
        machine-readable summary on ``self.chaos`` for bench.py's
        headline round trip.

        ``strict`` is the testbed's recovery assertion, now an SLO: a
        failed injection, ANY event with no commit after it, or a
        recovery slower than its fault class's SLO raises ParseError —
        commit progress must resume after every scripted fault *within
        budget* (plans are validated to leave the run-window headroom
        this needs; the table is logs/slo.json, else the defaults)."""
        from ..chaos import judge, summarize_recovery
        from ..chaos.recovery import event_label

        summary = summarize_recovery(events, self.commits.values())
        self.chaos = summary
        if summary["events"]:
            self.notes.append(
                f"Chaos plan: {len(summary['events'])} event(s), "
                f"max recovery {summary['max_recovery_ms']:g} ms")
        # graftsurge: goodput retained under each surge window, from the
        # committed-bytes timeline (the offered surge load itself rides
        # a separate generator whose log is outside the client glob).
        from ..chaos.plan import surge_window_s

        for e in summary["events"]:
            if e.get("action") != "surge" or e.get("wall") is None:
                continue
            dur = surge_window_s(e.get("params"))
            if dur <= 0:
                continue
            wall = float(e["wall"])
            before = self._window_tps(wall - dur, wall)
            during = self._window_tps(wall, wall + dur)
            e["goodput"] = {"before_tps": round(before, 1),
                            "during_tps": round(during, 1)}
            if before > 0:
                retained = during / before
                e["goodput"]["retained"] = round(retained, 3)
                self.notes.append(
                    f"Chaos {event_label(e)}: goodput retained "
                    f"{retained:.0%} under surge ({during:.0f} vs "
                    f"{before:.0f} tx/s)")
        for e in summary["events"]:
            label = f"Chaos {event_label(e)}"
            if not e["ok"]:
                self.notes.append(
                    f"{label}: injection FAILED ({e.get('error')})")
            elif e["recovered"]:
                self.notes.append(
                    f"{label}: recovery {e['recovery_ms']:g} ms")
            else:
                self.notes.append(
                    f"{label}: recovery UNCONFIRMED (no commit after "
                    "event)")
        verdict = judge(summary, slos)
        summary["slo"] = verdict
        for v in verdict["verdicts"]:
            if v["ok"]:
                self.notes.append(
                    f"Chaos SLO {v['class']}: {v['recovery_ms']:g} ms "
                    f"<= {v['slo_ms']:g} ms PASS")
            else:
                self.notes.append(
                    f"Chaos SLO {v['class']}: FAIL ({v['reason']})")
        if strict:
            if not summary["injected_ok"]:
                raise ParseError("chaos injection failed: " + "; ".join(
                    e.get("error", "?") for e in summary["events"]
                    if not e["ok"]))
            if not summary["recovered"]:
                raise ParseError(
                    "consensus did not resume after chaos event(s): "
                    + ", ".join(summary["unrecovered"]))
            if not verdict["ok"]:
                raise ParseError(
                    "chaos recovery SLO breached: " + "; ".join(
                        f"{v['class']} ({v['reason']})"
                        for v in verdict["verdicts"] if not v["ok"]))
            # graftview: a leader cascade that "recovered" without a
            # single TC forming means the drill never actually forced a
            # view change (wrong victims, or the round estimate tracked
            # nothing live) — the scripted scenario did not happen as
            # written, so strict mode fails it rather than passing a
            # drill that drilled nothing.
            cascades = [e for e in summary["events"]
                        if e.get("target") == "leader-cascade"
                        and e.get("ok")]
            if cascades and not (self.viewchange["tc_rounds"]
                                 or self.viewchange["transitions"]):
                raise ParseError(
                    "leader cascade executed but no TC formed and no "
                    "TC round transition was logged: the view-change "
                    "drill produced no view change")
            # graftfleet: a fleet-member kill that no node re-homed
            # away from means the failover ladder never engaged — the
            # drill drilled nothing (same idiom as the cascade check).
            from ..chaos.plan import sidecar_index

            fleet_kills = [
                e for e in summary["events"]
                if e.get("action") == "kill" and e.get("ok")
                and sidecar_index(str(e.get("target", ""))) is not None]
            if fleet_kills and not (self.failover or {}).get("rehomes"):
                raise ParseError(
                    "fleet sidecar kill executed but no node logged a "
                    "failover re-home: the endpoint ladder never "
                    "engaged")

    def print(self, filename):
        assert isinstance(filename, str)
        with open(filename, "a") as f:
            f.write(self.result())

    @classmethod
    def process(cls, directory, faults=0):
        assert isinstance(directory, str)
        import json

        clients = []
        for filename in sorted(glob(join(directory, "client-*.log"))):
            with open(filename, "r") as f:
                clients.append(f.read())
        nodes = []
        for filename in sorted(glob(join(directory, "node-*.log"))):
            with open(filename, "r") as f:
                nodes.append(f.read())
        # Executed fault events, written by the harness after the run
        # window (LocalBench._finish_fault_plan).  Presence switches the
        # parser into chaos mode: client deaths on faulted replicas are
        # tolerated, and the recovery assertion is STRICT — a chaos run
        # that stalled is a failed run.
        chaos_events = None
        try:
            with open(join(directory, "chaos-events.json")) as f:
                loaded = json.load(f)
            if isinstance(loaded, list):
                chaos_events = loaded
        except (OSError, ValueError):
            pass
        # Twins: logs of equivocating replicas (harness names them
        # twin-*.log, OUTSIDE the node glob) feed only the safety
        # assertion.
        twins = []
        for filename in sorted(glob(join(directory, "twin-*.log"))):
            with open(filename, "r") as f:
                twins.append(f.read())

        def _json_or_none(name):
            try:
                with open(join(directory, name)) as f:
                    loaded = json.load(f)
                return loaded if isinstance(loaded, dict) else None
            except (OSError, ValueError):
                return None

        parser = cls(clients, nodes, faults, chaos_events=chaos_events,
                     strict_chaos=chaos_events is not None, twins=twins,
                     wan=_json_or_none("wan.json"),
                     slos=_json_or_none("slo.json"))
        # The harness drops the sidecar's scheduler telemetry here at
        # teardown (LocalBench._fetch_sidecar_stats); a missing or
        # malformed file simply means no sidecar ran.
        try:
            with open(join(directory, "sidecar-stats.json")) as f:
                parser.note_sidecar_stats(json.load(f))
        except (OSError, ValueError):
            pass
        # graftfleet: per-endpoint snapshots (sidecar-stats-<i>.json);
        # each folds independently — the strict fairness/starvation
        # assertions hold for EVERY fleet member, and the _endpoint tag
        # the harness stamped prefixes that member's note lines.
        for filename in sorted(glob(join(directory,
                                         "sidecar-stats-*.json"))):
            try:
                with open(filename) as f:
                    parser.note_sidecar_stats(json.load(f))
            except (OSError, ValueError):
                continue
        # grafttrace: merge the run's spans (node TRACE lines + sidecar
        # JSONL + clock offsets) into the Perfetto-loadable trace.json
        # artifact and the commit critical-path notes, and fold the
        # sampled metrics time series in.  graftscope first folds the
        # C++ node's METRICS lines into metrics.jsonl (idempotent), so
        # the per-replica series rides the same artifact.  All
        # best-effort: a run that traced nothing parses exactly as
        # before.
        try:
            from ..obs import merge_node_series, read_samples, \
                write_run_trace

            summary = write_run_trace(directory)
            if summary is not None:
                parser.note_trace(summary)
            merge_node_series(directory)
            samples, torn = read_samples(join(directory, "metrics.jsonl"))
            parser.note_metrics(samples, malformed=torn)
        except (OSError, ValueError, TypeError, KeyError):
            pass
        return parser

"""Testbed settings loaded from settings.json
(benchmark/benchmark/settings.py:8-66 capability). Ports follow the
reference convention: consensus 8000, mempool 7000, front 6000.
"""

from __future__ import annotations

import json
from os.path import exists


class SettingsError(Exception):
    pass


class Settings:
    def __init__(self, testbed, key_name, key_path, base_port, repo_name,
                 repo_url, branch, instance_type, aws_regions, hosts=None):
        regions = (aws_regions if isinstance(aws_regions, list)
                   else [aws_regions])
        inputs_str = [testbed, key_name, key_path, repo_name, repo_url,
                      branch, instance_type] + regions
        if not all(isinstance(x, str) for x in inputs_str):
            raise SettingsError("Invalid settings types")
        if not isinstance(base_port, int):
            raise SettingsError("Invalid settings types")

        self.testbed = testbed
        self.key_name = key_name
        self.key_path = key_path
        self.base_port = base_port
        self.repo_name = repo_name
        self.repo_url = repo_url
        self.branch = branch
        self.instance_type = instance_type
        self.aws_regions = regions
        self.hosts = list(hosts or [])

    @classmethod
    def load(cls, filename="settings.json"):
        if not exists(filename):
            raise SettingsError(f"settings file {filename} not found")
        try:
            with open(filename, "r") as f:
                data = json.load(f)
            return cls(
                data["testbed"],
                data["key"]["name"],
                data["key"]["path"],
                data["ports"]["consensus"],
                data["repo"]["name"],
                data["repo"]["url"],
                data["repo"]["branch"],
                data["instances"]["type"],
                data["instances"]["regions"],
                hosts=data.get("hosts", []),
            )
        except (json.JSONDecodeError, KeyError) as e:
            raise SettingsError(f"Malformed settings: {e}")

"""Benchmark harness CLI — the `fab local/remote/plot/...` surface of the
reference (benchmark/fabfile.py:11-155) as a module entry point:

  python -m hotstuff_tpu.harness local [--nodes 4] [--rate 100000] ...
  python -m hotstuff_tpu.harness plot
  python -m hotstuff_tpu.harness aggregate
"""

from __future__ import annotations

import argparse
import sys


def cmd_local(args):
    from .config import BenchParameters, NodeParameters
    from .local import LocalBench
    from .utils import BenchError, PathMaker, Print

    bench_params = BenchParameters({
        "faults": args.faults,
        "nodes": [args.nodes],
        "rate": [args.rate],
        "tx_size": args.tx_size,
        "duration": args.duration,
        "tpu_sidecar": args.tpu_sidecar,
    })
    node_params = NodeParameters.default(
        tpu_sidecar=(f"127.0.0.1:{LocalBench.SIDECAR_PORT}"
                     if args.tpu_sidecar else None))
    node_params.json["mempool"]["batch_size"] = args.batch_size
    node_params.json["consensus"]["timeout_delay"] = args.timeout
    try:
        ret = LocalBench(bench_params, node_params).run(debug=args.debug)
        print(ret.result())
        if args.output:
            ret.print(args.output)
    except BenchError as e:
        Print.error(e)
        sys.exit(1)


def cmd_aggregate(args):
    from .aggregate import LogAggregator

    LogAggregator(max_latencies=args.max_latency).print()
    print("aggregated series written to plots/")


def cmd_plot(args):
    from .aggregate import LogAggregator
    from .plot import Ploter, PlotError

    LogAggregator(max_latencies=args.max_latency).print()
    try:
        ploter = Ploter()
        ploter.plot_latency()
        ploter.plot_robustness()
        if args.max_latency:
            ploter.plot_tps()
        print("plots written to plots/")
    except PlotError as e:
        print(f"plot failed: {e}")
        sys.exit(1)


def cmd_logs(args):
    from .logs import LogParser, ParseError

    try:
        parser = LogParser.process(args.directory, faults=args.faults)
        print(parser.result())
    except ParseError as e:
        print(f"parse failed: {e}")
        sys.exit(1)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="hotstuff_tpu.harness")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("local", help="run a local 4-node benchmark")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--rate", type=int, default=100_000)
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=15_000)
    p.add_argument("--timeout", type=int, default=1_000)
    p.add_argument("--duration", type=int, default=30, help="seconds")
    p.add_argument("--tpu-sidecar", action="store_true",
                   help="route QC verification through the TPU sidecar")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--output", help="append summary to this result file")
    p.set_defaults(func=cmd_local)

    p = sub.add_parser("aggregate", help="aggregate results/ into series")
    p.add_argument("--max-latency", type=int, nargs="*", default=[])
    p.set_defaults(func=cmd_aggregate)

    p = sub.add_parser("plot", help="aggregate + plot")
    p.add_argument("--max-latency", type=int, nargs="*", default=[])
    p.set_defaults(func=cmd_plot)

    p = sub.add_parser("logs", help="parse a logs directory")
    p.add_argument("directory", nargs="?", default="logs")
    p.add_argument("--faults", type=int, default=0)
    p.set_defaults(func=cmd_logs)

    args = ap.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()

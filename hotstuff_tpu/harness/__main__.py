"""Benchmark harness CLI — the `fab local/remote/plot/...` surface of the
reference (benchmark/fabfile.py:11-155) as a module entry point:

  python -m hotstuff_tpu.harness local [--nodes 4] [--rate 100000] ...
  python -m hotstuff_tpu.harness plot
  python -m hotstuff_tpu.harness aggregate
"""

from __future__ import annotations

import argparse
import sys


def cmd_local(args):
    from .config import BenchParameters, NodeParameters
    from .local import LocalBench
    from .utils import BenchError, Print

    use_sidecar = (args.tpu_sidecar or args.sidecar_host_crypto
                   or args.scheme == "bls")
    bench_params = BenchParameters({
        "faults": args.faults,
        "nodes": [args.nodes],
        "rate": [args.rate],
        "tx_size": args.tx_size,
        "duration": args.duration,
        "tpu_sidecar": use_sidecar,
        "sidecar_host_crypto": args.sidecar_host_crypto,
        "sidecar_warm_rlc": args.warm_rlc,
        "sidecar_mesh": args.sidecar_mesh,
        "scheme": args.scheme,
        "fault_plan": args.fault_plan,
        "wan": args.wan,
        "slo": args.slo,
        "twins": args.twins,
    })
    node_params = NodeParameters.default(
        tpu_sidecar=(f"127.0.0.1:{LocalBench.SIDECAR_PORT}"
                     if use_sidecar else None),
        scheme=args.scheme if args.scheme != "ed25519" else None,
        chain=args.chain, dag=args.dag)
    node_params.json["mempool"]["batch_size"] = args.batch_size
    node_params.json["mempool"]["max_batch_delay"] = args.batch_delay
    node_params.json["consensus"]["timeout_delay"] = args.timeout
    try:
        ret = LocalBench(bench_params, node_params).run(debug=args.debug)
        print(ret.result())
        if args.output:
            ret.print(args.output)
    except BenchError as e:
        Print.error(e)
        sys.exit(1)


def cmd_aggregate(args):
    from .aggregate import LogAggregator

    agg = LogAggregator(max_latencies=args.max_latency)
    agg.print()
    agg.print_matrix()
    agg.print_bands()
    print("aggregated series + matrix written to plots/")


def cmd_plot(args):
    from .aggregate import LogAggregator
    from .plot import Ploter, PlotError

    agg = LogAggregator(max_latencies=args.max_latency)
    agg.print()
    agg.print_matrix()
    try:
        ploter = Ploter()
        ploter.plot_latency()
        ploter.plot_robustness()
        if args.max_latency:
            ploter.plot_tps()
        try:
            ploter.plot_matrix()
        except PlotError:
            pass  # a single-cell matrix has nothing to draw
        # grafttrace artifacts from the LAST run's logs dir (per-stage
        # latency histograms + the sampled metrics time series); absent
        # when the last run predates tracing or booted no sidecar.
        for fn in (ploter.plot_trace, ploter.plot_metrics):
            try:
                fn()
            except PlotError:
                pass
        print("plots written to plots/")
    except PlotError as e:
        print(f"plot failed: {e}")
        sys.exit(1)


def cmd_logs(args):
    from .logs import LogParser, ParseError

    try:
        parser = LogParser.process(args.directory, faults=args.faults)
        print(parser.result())
    except ParseError as e:
        print(f"parse failed: {e}")
        sys.exit(1)


def _load_settings(args):
    from .settings import Settings, SettingsError
    from .utils import BenchError

    try:
        return Settings.load(args.settings)
    except SettingsError as e:
        raise BenchError("Failed to load settings", e)


def _resolve_hosts(args, settings):
    """Explicit --hosts beats settings.json's \"hosts\" list beats the
    cloud inventory (remote.py:31-50 host discovery analogue)."""
    if args.hosts:
        return args.hosts
    if settings.hosts:
        return settings.hosts
    from .instance import InstanceManager

    return InstanceManager(settings).hosts()


def cmd_remote(args):
    from .config import BenchParameters, ConfigError, NodeParameters
    from .remote import Bench
    from .utils import BenchError, Print

    try:
        settings = _load_settings(args)
        hosts = _resolve_hosts(args, settings)
        bench_params = BenchParameters({
            "faults": args.faults,
            "nodes": args.nodes,
            "rate": args.rate,
            "tx_size": args.tx_size,
            "duration": args.duration,
            "runs": args.runs,
        })
        node_params = NodeParameters.default(chain=args.chain)
        bench = Bench(settings, hosts, user=args.user,
                      fault_plan=args.fault_plan, wan=args.wan,
                      slos=args.slo)
        if args.install:
            bench.install()
        if args.update:
            bench.update()
        bench.run(bench_params, node_params, debug=args.debug)
    except ConfigError as e:
        Print.error(BenchError("Invalid benchmark parameters", e))
        sys.exit(1)
    except BenchError as e:
        Print.error(e)
        sys.exit(1)


def cmd_install(args):
    from .remote import Bench
    from .utils import BenchError, Print

    try:
        settings = _load_settings(args)
        hosts = _resolve_hosts(args, settings)
        Bench(settings, hosts, user=args.user).install()
    except BenchError as e:
        Print.error(e)
        sys.exit(1)


def cmd_kill(args):
    """Stop every node/client on the fleet (fabfile.py kill analogue)."""
    from .remote import Bench
    from .utils import BenchError, Print

    try:
        settings = _load_settings(args)
        hosts = _resolve_hosts(args, settings)
        Bench(settings, hosts, user=args.user).kill()
        Print.info(f"killed node/client processes on {len(hosts)} host(s)")
    except BenchError as e:
        Print.error(e)
        sys.exit(1)


def cmd_cloud(args):
    """AWS instance lifecycle (fabfile.py create/destroy/start/stop/info
    analogue); requires boto3 + credentials."""
    from .instance import InstanceManager
    from .utils import BenchError, Print

    try:
        settings = _load_settings(args)
        manager = InstanceManager(settings)
        if args.action == "create":
            manager.create_instances(args.instances)
        elif args.action == "destroy":
            manager.terminate_instances()
        elif args.action == "start":
            manager.start_instances()
        elif args.action == "stop":
            manager.stop_instances()
        elif args.action == "info":
            manager.print_info()
    except BenchError as e:
        Print.error(e)
        sys.exit(1)
    except Exception as e:  # boto3/botocore errors (no credentials, API)
        Print.error(BenchError("Cloud operation failed", e))
        sys.exit(1)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="hotstuff_tpu.harness")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("local", help="run a local 4-node benchmark")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--rate", type=int, default=100_000)
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=15_000)
    p.add_argument("--batch-delay", type=int, default=100,
                   help="mempool max batch delay (ms)")
    p.add_argument("--timeout", type=int, default=1_000)
    p.add_argument("--duration", type=int, default=30, help="seconds")
    p.add_argument("--sidecar-host-crypto", action="store_true",
                   help="run the sidecar with --host-crypto (no device; "
                        "also the automatic fallback when the device "
                        "sidecar never becomes ready)")
    p.add_argument("--tpu-sidecar", action="store_true",
                   help="route QC verification through the TPU sidecar")
    p.add_argument("--sidecar-mesh", type=int, default=0, metavar="N",
                   help="run the sidecar with --mesh N --warm-rlc-sharded "
                        "(shard verify launches over an N-device mesh and "
                        "route coalesced batches through the sharded "
                        "one-MSM path; 0 = single device)")
    p.add_argument("--warm-rlc", action="store_true",
                   help="also pre-compile the sidecar's one-MSM RLC "
                        "shapes so coalesced batches route through the "
                        "combined check (adds boot-time compiles, cached "
                        "across restarts)")
    p.add_argument("--chain", type=int, choices=range(2, 9), default=2,
                   metavar="K",
                   help="commit-rule depth: k-chain in [2, 8] (default 2)")
    p.add_argument("--dag", action="store_true",
                   help="graftdag certified-batch mempool: proposals carry "
                        "availability certificates (2f+1 signed batch "
                        "ACKs) instead of relying on payload sync, and "
                        "the leader pipelines rounds without waiting for "
                        "broadcast ACKs")
    p.add_argument("--scheme", choices=["ed25519", "bls"],
                   default="ed25519",
                   help="signature scheme (bls implies --tpu-sidecar)")
    p.add_argument("--fault-plan", default=None, metavar="PATH|SPEC",
                   help="graftchaos fault plan to execute against the "
                        "running bench: a JSON file, or an inline spec "
                        "like '5 sidecar kill; 10 sidecar restart; "
                        "12 node:1 pause; 15 node:1 resume' (times are "
                        "seconds into the run window; the summary "
                        "reports per-fault recovery latency)")
    p.add_argument("--wan", default=None, metavar="PATH|SPEC",
                   help="graftwan link-shape spec (chaos/netem.py): a "
                        "JSON file or inline DSL like 'node:0>sidecar "
                        "latency_ms=40 loss_pct=0.5 name=sc'; realized "
                        "locally by userspace WanProxy instances, so "
                        "link:<name> fault-plan events can partition/"
                        "heal the named links")
    p.add_argument("--slo", default=None, metavar="PATH|SPEC",
                   help="per-fault-class recovery SLO table overrides "
                        "(chaos/slo.py): a JSON file or inline "
                        "'node-kill=8000; link-heal=3000' (ms); chaos "
                        "recovery is judged pass/fail against the table")
    p.add_argument("--twins", action="store_true",
                   help="boot a Twins-style equivocating sibling of "
                        "replica 0 (same keypair, own ports; the honest "
                        "committee splits across the two views) and "
                        "hold the run to the strict no-conflicting-"
                        "commits safety assertion")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--output", help="append summary to this result file")
    p.set_defaults(func=cmd_local)

    p = sub.add_parser("aggregate", help="aggregate results/ into series")
    p.add_argument("--max-latency", type=int, nargs="*", default=[])
    p.set_defaults(func=cmd_aggregate)

    p = sub.add_parser("plot", help="aggregate + plot")
    p.add_argument("--max-latency", type=int, nargs="*", default=[])
    p.set_defaults(func=cmd_plot)

    p = sub.add_parser("logs", help="parse a logs directory")
    p.add_argument("directory", nargs="?", default="logs")
    p.add_argument("--faults", type=int, default=0)
    p.set_defaults(func=cmd_logs)

    def add_fleet_args(p):
        p.add_argument("--settings", default="settings.json")
        p.add_argument("--hosts", nargs="*", default=[],
                       help="override host list (else settings.json "
                            "'hosts', else the cloud inventory)")
        p.add_argument("--user", default="ubuntu")

    p = sub.add_parser("remote",
                       help="multi-host benchmark matrix over ssh")
    add_fleet_args(p)
    p.add_argument("--nodes", type=int, nargs="+", default=[4])
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--rate", type=int, nargs="+", default=[50_000])
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--duration", type=int, default=30)
    p.add_argument("--runs", type=int, default=1)
    p.add_argument("--chain", type=int, choices=range(2, 9), default=2,
                   metavar="K",
                   help="commit-rule depth: k-chain in [2, 8] (default 2)")
    p.add_argument("--install", action="store_true",
                   help="install toolchain on hosts first")
    p.add_argument("--update", action="store_true",
                   help="git pull + rebuild on hosts first")
    p.add_argument("--fault-plan", default=None, metavar="PATH|SPEC",
                   help="graftchaos fault plan executed across the fleet "
                        "mid-run over ssh (same schema as local)")
    p.add_argument("--wan", default=None, metavar="PATH|SPEC",
                   help="graftwan link-shape spec compiled to per-host "
                        "'tc qdisc netem' egress shaping (same schema "
                        "as local; needs sudo tc on the hosts)")
    p.add_argument("--slo", default=None, metavar="PATH|SPEC",
                   help="per-fault-class recovery SLO table overrides "
                        "(same schema as local)")
    p.add_argument("--debug", action="store_true")
    p.set_defaults(func=cmd_remote)

    p = sub.add_parser("install", help="install toolchain on the fleet")
    add_fleet_args(p)
    p.set_defaults(func=cmd_install)

    p = sub.add_parser("kill", help="kill node/client on the fleet")
    add_fleet_args(p)
    p.set_defaults(func=cmd_kill)

    for action, help_text in [
        ("create", "create cloud instances"),
        ("destroy", "terminate cloud instances"),
        ("start", "start stopped cloud instances"),
        ("stop", "stop cloud instances"),
        ("info", "print cloud instance info"),
    ]:
        p = sub.add_parser(action, help=help_text)
        p.add_argument("--settings", default="settings.json")
        if action == "create":
            p.add_argument("--instances", type=int, default=2,
                           help="instances per region")
        p.set_defaults(func=cmd_cloud, action=action)

    args = ap.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()

"""graftlint ring checker: cadence tick-body discipline (graftcadence).

The resident ring's whole value is a BOUNDED, steady-state tick: every
cadence tick expires, collects, and arms within the guard's deadline
class, so the loop's wall is always a few guarded launches — never a
park.  Two structural hazards would silently break that:

  * an unbounded wait inside the tick body — one hung ``.result()`` /
    ``.wait()`` outside the guard's deadline helper parks the ring (and
    with it the engine thread and every queued consensus verify), which
    is exactly the wedge class graftguard exists to preempt;

  * a launch of an UNWARMED shape inside the tick — the ring's contract
    is ONE resident compiled program per warmed ShapeRegistry bucket,
    re-dispatched at cadence.  A direct ``verify_batch``-family call
    picks its own compile bucket, so a single odd-shaped tick smuggles
    a fresh XLA compile (seconds to minutes) into a loop whose deadline
    class is the warm grace — a guaranteed false wedge.

The type system cannot hold either invariant; this checker holds both
mechanically, as the single rule ``blocking-call-in-ring-tick``.

Scope: methods of ring classes (a ``ClassDef`` whose name contains
``Ring``) in the scanned modules.  Waits lexically inside the thunks
handed TO the guard (``engine._guarded(...)`` / ``<guard>.call(...)``
argument subtrees) are by definition supervised — the monitor preempts
them — so those subtrees are exempt, same as the guard checker.  The
legal launch routes are the engine's own pack worker (``engine._pack``,
warmed registry buckets by construction) and the fixed-shape resident
entry ``ring_slot_pack``; everything in ``_FRESH_COMPILE_CALLS`` picks
its own bucket and is banned from tick bodies.
"""

from __future__ import annotations

import ast
import glob as _glob
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar/ring.py",
)

_WAIT_ATTRS = {"result", "exception", "wait"}

# Launch entries that choose their own compile bucket from the batch
# shape: legal in the staged engine (whose deadline class tolerates a
# compile), illegal inside a cadence tick (warm-grace deadline class;
# the ring must route through engine._pack or ring_slot_pack).
_FRESH_COMPILE_CALLS = {
    "verify_batch",
    "verify_batch_rlc",
    "verify_batch_sharded",
    "verify_batch_sharded_pack",
    "verify_rlc_sharded",
    "verify_rlc_sharded_pack",
    "verify_sharded_chunked",
    "verify_sharded_chunked_pack",
    "make_sharded_verifier",
}


def _is_unbounded_wait(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _WAIT_ATTRS:
        return False
    if node.args:
        return False  # positional timeout (Event.wait(t), cv.wait(t))
    if any(kw.arg == "timeout" for kw in node.keywords):
        return False
    return True


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _names_guard(node: ast.expr) -> bool:
    while isinstance(node, ast.Attribute):
        if "guard" in node.attr.lower():
            return True
        node = node.value
    return isinstance(node, ast.Name) and "guard" in node.id.lower()


def _is_guard_entry(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "_guarded":
            return True
        if func.attr == "call" and _names_guard(func.value):
            return True
    return isinstance(func, ast.Name) and func.id == "_guarded"


def _ring_bodies(tree: ast.AST):
    """Yield every method body of every ring class in the module."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and "ring" in node.name.lower():
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield item


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    for fn in _ring_bodies(tree):
        supervised: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _is_guard_entry(node):
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    for child in ast.walk(arg):
                        supervised.add(id(child))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in supervised:
                continue
            if _is_unbounded_wait(node):
                findings.append(Finding(
                    path, node.lineno, "blocking-call-in-ring-tick",
                    f"unbounded .{node.func.attr}() wait inside ring "
                    f"tick body {fn.name}: one hung call parks the "
                    "cadence loop and every queued consensus verify "
                    "behind it — route it through self.engine._guarded "
                    "(the tick deadline class), or bound it with a "
                    "timeout"))
            elif _call_name(node) in _FRESH_COMPILE_CALLS:
                findings.append(Finding(
                    path, node.lineno, "blocking-call-in-ring-tick",
                    f"{_call_name(node)}() inside ring tick body "
                    f"{fn.name} picks its own compile bucket: an "
                    "odd-shaped tick smuggles a fresh XLA compile into "
                    "the warm-grace deadline class (guaranteed false "
                    "wedge) — arm through engine._pack (warmed "
                    "registry buckets) or ring_slot_pack (the "
                    "fixed-shape resident entry)"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        for path in sorted(_glob.glob(os.path.join(root, target))):
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

"""graftlint tenant-scoped-queue checker: scheduler code must never
reach around the DRR tenant lanes with raw deque operations.

graftfleet made the tenant id a real scheduling key: every class queue
is backed by per-tenant FIFO lanes drained in deficit-round-robin order
(sched/tenantq.py), and the per-tenant admission caps plus the
``tenant_starvation == 0`` invariant only hold if EVERY queue access
routes through the lane helpers (``_offer_locked`` / ``head_locked`` /
``pop_next_locked``).  One ``self.items.popleft()`` in a scheduler
method would silently collapse the three-key discipline back to a
single shared FIFO: the code would still look queue-shaped in review,
and the first greedy tenant would blockade every other tenant's
latency-class requests.  This rule makes that bypass a lint finding
instead of a noisy-neighbor incident.

Rule:
  tenant-unscoped-queue   in a sched/ module OUTSIDE tenantq.py,
                          (a) a ``.popleft`` / ``.appendleft`` /
                          ``.rotate`` call whose receiver is a
                          queue-carrying attribute (``items`` /
                          ``queue`` / ``queues`` / ``lanes`` /
                          ``order`` / ``backlog`` / ``pending``), or
                          (b) a ``self``-rooted subscript of such an
                          attribute (``self.items[0]`` — peeking past
                          the DRR head).

Receiver detection is name-based like the bounded-ingress rule: the
scheduler uses these conventional names for its admission-guarded
queues, and a rename that dodges the rule is exactly the edit a
reviewer should see.  Telemetry rings (``_pack_window``, ``_packs``)
and plain containers on value objects (a launch record's ``items``
list, read by index for pad accounting) use other names or non-``self``
receivers and stay out of scope by construction.  tenantq.py itself is
the audited implementation and is exempt wholesale.  Inline
``# graftlint: disable=tenant-unscoped-queue`` suppressions follow the
standard policy (analysis/README.md): only with a worked justification.
"""

from __future__ import annotations

import ast
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar/sched",
)

# The audited lane implementation: raw deque ops ARE its job.
_EXEMPT_FILES = ("tenantq.py",)

_RAW_OPS = {"popleft", "appendleft", "rotate"}
_QUEUE_NAMES = {"items", "queue", "queues", "lanes", "order", "backlog",
                "pending"}
# Subscripts police only the deque-shaped attributes: ``self.items[0]``
# peeks past the DRR head, while ``self._queues[cls]`` merely SELECTS a
# class queue object (a dict lookup, not an ordering decision).
_DEQUE_NAMES = {"items", "order"}


def _queue_attr(node: ast.AST):
    """Rightmost queue-ish attribute name of a receiver
    (``self.items.popleft`` -> ``items``), else None.  Attribute
    receivers only — a local deque is function-private state."""
    if isinstance(node, ast.Attribute) and \
            node.attr.lstrip("_") in _QUEUE_NAMES:
        return node.attr
    return None


def _self_rooted(node: ast.AST) -> bool:
    """True when the attribute chain bottoms out at ``self`` — the
    shared-state access the rule polices (``launch.items[...]`` on a
    value object is mere data plumbing)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


def _check_source(rel: str, source: str) -> list:
    findings = []
    tree = parse_source(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr in _RAW_OPS):
                continue
            queue = _queue_attr(fn.value)
            if queue is None:
                continue
            findings.append(Finding(
                rel, node.lineno, "tenant-unscoped-queue",
                f"raw .{fn.attr}() on queue attribute {queue!r} bypasses "
                "the DRR tenant lanes: scheduler queues drain only "
                "through tenantq's _offer_locked/head_locked/"
                "pop_next_locked so per-tenant fairness and the "
                "starvation invariant can never be sidestepped"))
        elif isinstance(node, ast.Subscript):
            value = node.value
            if not (isinstance(value, ast.Attribute)
                    and value.attr.lstrip("_") in _DEQUE_NAMES
                    and _self_rooted(value)):
                continue
            queue = value.attr
            findings.append(Finding(
                rel, node.lineno, "tenant-unscoped-queue",
                f"subscript of queue attribute {queue!r} peeks past the "
                "DRR head: the next record to serve is tenantq's "
                "head_locked()/pop_next_locked() decision, not "
                "whatever sits at a raw index"))
    return findings


def _iter_targets(root: str, targets):
    for rel in targets:
        path = os.path.join(root, rel)
        if os.path.isfile(path):
            yield rel, path
        elif os.path.isdir(path):
            for dirpath, _dirnames, filenames in os.walk(path):
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        yield os.path.relpath(full, root), full


def check_sources(sources: dict) -> list:
    """Lint a {path: python source} mapping (unit-test entry point).
    The exemption follows the tree walk: a tenantq.py entry is the
    audited lane implementation wherever it sits."""
    findings = []
    for rel, source in sources.items():
        if os.path.basename(rel) in _EXEMPT_FILES:
            continue
        findings += _check_source(rel, source)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    findings = []
    sources = {}
    for rel, path in _iter_targets(root, targets):
        if os.path.basename(rel) in _EXEMPT_FILES:
            continue
        try:
            source = read_source(path)
        except OSError:
            continue
        sources[rel] = source
        try:
            findings += _check_source(rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rel, e.lineno or 1, "tenant-unscoped-queue",
                f"cannot parse module: {e.msg}"))
    return apply_suppressions(findings, sources)

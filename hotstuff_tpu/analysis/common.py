"""Shared graftlint plumbing: findings, suppressions, constant parsing,
and the per-run parse/read caches every checker shares."""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ---------------------------------------------------------------------------
# Per-run parse + read caches
#
# Seven checkers scan overlapping target sets (sidecar/service.py alone
# is parsed by hotpath, padshape, sockets, obsspan and threads), and the
# gate used to pay a fresh open() + ast.parse() per checker per file.
# Both are memoized here instead: parse_source keys on the (path, source)
# pair — so unit-test fixtures that lint many different sources under one
# fake path never collide — and read_source keys on (abspath, mtime) so a
# file edited between two in-process runs is re-read.  One process run of
# `python -m hotstuff_tpu.analysis` therefore parses each module exactly
# once no matter how many rules visit it.
# ---------------------------------------------------------------------------

_PARSE_CACHE: dict = {}
_READ_CACHE: dict = {}


def parse_source(source: str, path: str = "<src>") -> ast.Module:
    """``ast.parse`` memoized on (path, source).  All AST rules route
    through this so a multi-checker run parses each file once."""
    key = (path, source)
    tree = _PARSE_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source, filename=path)
        _PARSE_CACHE[key] = tree
    return tree


def read_source(abspath: str) -> str:
    """Read a source file, memoized on (path, mtime)."""
    try:
        mtime = os.stat(abspath).st_mtime_ns
    except OSError:
        mtime = None
    key = (abspath, mtime)
    text = _READ_CACHE.get(key)
    if text is None:
        with open(abspath, encoding="utf-8") as fh:
            text = fh.read()
        _READ_CACHE[key] = text
    return text


def clear_caches():
    """Drop both caches (long-lived embedders; the CLI never needs to)."""
    _PARSE_CACHE.clear()
    _READ_CACHE.clear()


_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w\-, ]+)")


def suppressed_rules(source: str) -> dict:
    """line number (1-based) -> set of rule names silenced on that line.

    A ``# graftlint: disable=rule[,rule2]`` comment silences its own line
    AND the following line (so a suppression can sit above a long
    statement)."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        out.setdefault(i + 1, set()).update(rules)
    return out


def apply_suppressions(findings, sources: dict):
    """Drop findings silenced by an inline comment in their source file.

    ``sources`` maps finding.path -> file text; findings whose path is
    unknown pass through unfiltered (C++/CMake findings — those use
    constants-level gating, not comments)."""
    cache = {p: suppressed_rules(src) for p, src in sources.items()}
    kept = []
    for f in findings:
        silenced = cache.get(f.path, {}).get(f.line, set())
        if f.rule in silenced:
            continue
        kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# Python constant scraping (AST; no imports, so fixtures and broken trees
# can still be linted)
# ---------------------------------------------------------------------------

_BIN_OPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Pow: lambda a, b: a ** b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.Mod: lambda a, b: a % b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitXor: lambda a, b: a ^ b,
}


def _eval_int(node: ast.AST, env: dict):
    """Evaluate a constant integer expression; raises ValueError when the
    expression isn't statically evaluable (calls, attributes, floats)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise ValueError(f"unknown name {node.id}")
    if isinstance(node, ast.BinOp) and type(node.op) in _BIN_OPS:
        return _BIN_OPS[type(node.op)](_eval_int(node.left, env),
                                       _eval_int(node.right, env))
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_eval_int(node.operand, env)
    raise ValueError(f"not a static int expression: {ast.dump(node)[:60]}")


def module_int_constants(source: str, path: str = "<src>") -> dict:
    """Top-level ``NAME = <int expr>`` assignments of a module, evaluated
    in order so later constants may reference earlier ones."""
    tree = parse_source(source, path)
    env: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            name = node.target.id
        else:
            continue
        try:
            env[name] = _eval_int(node.value, env)
        except ValueError:
            continue
    return env

"""graftlint wire/constants cross-checker.

The Python sidecar (``sidecar/protocol.py``) and the C++ node
(``native/src/crypto/sidecar_client.cpp``) speak a hand-rolled binary
protocol, and the curve arithmetic keeps its field moduli duplicated
between the device ops, the host reference implementations, and (as
documentation constants) the C++ crypto layer.  No test exercises both
sides of every constant — a one-sided edit ships a node that corrupts
QCs on the wire.  This pass parses both trees (AST for Python, regex for
the C++ — clang-free by design) and asserts they agree.

Rules:
  wire-tag-mismatch       sidecar opcode values differ (or are missing)
                          between protocol.py and sidecar_client.cpp
  wire-length-mismatch    fixed record sizes differ: digest, Ed25519
                          pk/sig, BLS pk/sig/sk byte lengths
  field-modulus-mismatch  the 2^255-19 / BLS12-381 field modulus
                          literals disagree across ops/field25519.py,
                          utils/intmath.py, ops/field381.py,
                          offchain/bls12381.py and crypto.hpp
"""

from __future__ import annotations

import os
import re

from .common import Finding, module_int_constants

# (python constant in protocol.py, C++ constant in sidecar_client.cpp)
_TAG_PAIRS = (
    ("OP_VERIFY_BATCH", "kOpVerifyBatch"),
    ("OP_BLS_VERIFY_AGG", "kOpBlsVerifyAgg"),
    ("OP_BLS_SIGN", "kOpBlsSign"),
    ("OP_BLS_VERIFY_VOTES", "kOpBlsVerifyVotes"),
    ("OP_BLS_VERIFY_MULTI", "kOpBlsVerifyMulti"),
)

_LEN_PAIRS = (
    ("BLS_PK_LEN", "kBlsPkLen"),
    ("BLS_SIG_LEN", "kBlsSigLen"),
    ("BLS_SK_LEN", "kBlsSkLen"),
    ("DIGEST_LEN", "kDigestLen"),
)

PROTOCOL = "hotstuff_tpu/sidecar/protocol.py"
SIDECAR_CLIENT = "native/src/crypto/sidecar_client.cpp"
CRYPTO_HPP = "native/src/crypto/crypto.hpp"
FIELD25519 = "hotstuff_tpu/ops/field25519.py"
INTMATH = "hotstuff_tpu/utils/intmath.py"
FIELD381 = "hotstuff_tpu/ops/field381.py"
BLS12381 = "hotstuff_tpu/offchain/bls12381.py"


def _read(root: str, rel: str):
    path = os.path.join(root, rel)
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _line_of(source: str, pattern: str) -> int:
    m = re.search(pattern, source, re.MULTILINE)
    return source[:m.start()].count("\n") + 1 if m else 1


def cpp_int_constants(source: str) -> dict:
    """``constexpr <type> kName = <int>;`` declarations (dec or hex)."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+[\w:]+\s+(k\w+)\s*=\s*(0[xX][0-9a-fA-F']+|\d+)",
            source):
        out[m.group(1)] = int(m.group(2).replace("'", ""), 0)
    return out


def cpp_hex_string_constants(source: str) -> dict:
    """``constexpr char kName[] = "hex" "hex"...;`` -> int value."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+char\s+(k\w+)\[\]\s*=\s*(?://[^\n]*)?"
            r"((?:\s*\"[0-9a-fA-F]*\")+)",
            source):
        digits = "".join(re.findall(r'"([0-9a-fA-F]*)"', m.group(2)))
        if digits:
            out[m.group(1)] = int(digits, 16)
    return out


def cpp_struct_array_len(source: str, struct: str) -> int | None:
    """Byte length of ``std::array<uint8_t, N> data`` inside a struct."""
    m = re.search(r"struct\s+%s\b.*?std::array<uint8_t,\s*(\d+)>\s+data"
                  % re.escape(struct), source, re.DOTALL)
    return int(m.group(1)) if m else None


def cpp_signature_lens(source: str) -> set:
    """The wire lengths Signature::deserialize accepts."""
    m = re.search(r"data\.size\(\)\s*!=\s*(\d+)\s*&&\s*"
                  r"s\.data\.size\(\)\s*!=\s*(\d+)", source)
    if not m:
        return set()
    return {int(m.group(1)), int(m.group(2))}


def check(root: str) -> list:
    findings: list[Finding] = []

    def miss(path, rule, what):
        findings.append(Finding(path, 1, rule, f"{what} not found — the "
                                "cross-check cannot anchor; fix the "
                                "source or update wirecheck.py"))

    proto_src = _read(root, PROTOCOL)
    client_src = _read(root, SIDECAR_CLIENT)
    crypto_src = _read(root, CRYPTO_HPP)
    if proto_src is None or client_src is None or crypto_src is None:
        for rel, src in ((PROTOCOL, proto_src), (SIDECAR_CLIENT, client_src),
                         (CRYPTO_HPP, crypto_src)):
            if src is None:
                miss(rel, "wire-tag-mismatch", "source file")
        return findings

    py = module_int_constants(proto_src, PROTOCOL)
    cpp = cpp_int_constants(client_src)
    cpp.update(cpp_int_constants(crypto_src))

    # -- message tags ------------------------------------------------------
    for py_name, cpp_name in _TAG_PAIRS:
        if py_name not in py:
            miss(PROTOCOL, "wire-tag-mismatch", f"constant {py_name}")
        elif cpp_name not in cpp:
            miss(SIDECAR_CLIENT, "wire-tag-mismatch", f"constant {cpp_name}")
        elif py[py_name] != cpp[cpp_name]:
            findings.append(Finding(
                SIDECAR_CLIENT, _line_of(client_src, cpp_name),
                "wire-tag-mismatch",
                f"{cpp_name}={cpp[cpp_name]} but {PROTOCOL} "
                f"{py_name}={py[py_name]}: the node and the sidecar "
                "disagree on a message opcode"))

    # -- fixed byte lengths ------------------------------------------------
    for py_name, cpp_name in _LEN_PAIRS:
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif cpp_name not in cpp:
            miss(SIDECAR_CLIENT, "wire-length-mismatch",
                 f"constant {cpp_name}")
        elif py[py_name] != cpp[cpp_name]:
            findings.append(Finding(
                SIDECAR_CLIENT, _line_of(client_src, cpp_name),
                "wire-length-mismatch",
                f"{cpp_name}={cpp[cpp_name]} but {PROTOCOL} "
                f"{py_name}={py[py_name]}: record framing will desync"))

    digest_len = cpp_struct_array_len(crypto_src, "Digest")
    pk_len = cpp_struct_array_len(crypto_src, "PublicKey")
    sig_lens = cpp_signature_lens(crypto_src)
    checks = (
        ("DIGEST_LEN", digest_len, "struct Digest byte length"),
        ("ED_PK_LEN", pk_len, "struct PublicKey byte length"),
    )
    for py_name, cpp_val, what in checks:
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif cpp_val is None:
            miss(CRYPTO_HPP, "wire-length-mismatch", what)
        elif py[py_name] != cpp_val:
            findings.append(Finding(
                CRYPTO_HPP, _line_of(crypto_src, "struct " + (
                    "Digest" if py_name == "DIGEST_LEN" else "PublicKey")),
                "wire-length-mismatch",
                f"{what} is {cpp_val} but {PROTOCOL} "
                f"{py_name}={py[py_name]}"))
    for py_name, lens_needed in (("ED_SIG_LEN", sig_lens),
                                 ("BLS_SIG_LEN", sig_lens)):
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif not lens_needed:
            miss(CRYPTO_HPP, "wire-length-mismatch",
                 "Signature::deserialize length check")
        elif py[py_name] not in lens_needed:
            findings.append(Finding(
                CRYPTO_HPP, _line_of(crypto_src, "bad signature length"),
                "wire-length-mismatch",
                f"Signature::deserialize accepts {sorted(lens_needed)} "
                f"but {PROTOCOL} {py_name}={py[py_name]}"))

    # -- field moduli ------------------------------------------------------
    hexes = cpp_hex_string_constants(crypto_src)
    moduli = {
        "P25519": (
            "kEd25519FieldPrimeHex",
            [(FIELD25519, "P"), (INTMATH, "P")],
        ),
        "Q381": (
            "kBls381FieldPrimeHex",
            [(FIELD381, "Q"), (BLS12381, "Q")],
        ),
    }
    for label, (cpp_name, py_sites) in moduli.items():
        values = {}
        for rel, const in py_sites:
            src = _read(root, rel)
            if src is None:
                miss(rel, "field-modulus-mismatch", "source file")
                continue
            consts = module_int_constants(src, rel)
            if const not in consts:
                miss(rel, "field-modulus-mismatch", f"constant {const}")
                continue
            values[rel] = (consts[const], _line_of(src, rf"^{const}\s*="))
        if cpp_name not in hexes:
            miss(CRYPTO_HPP, "field-modulus-mismatch",
                 f"constant {cpp_name}")
        else:
            values[CRYPTO_HPP] = (hexes[cpp_name],
                                  _line_of(crypto_src, cpp_name))
        if len({v for v, _ in values.values()}) > 1:
            detail = "; ".join(f"{rel} has {hex(v)[:18]}..."
                               for rel, (v, _) in sorted(values.items()))
            for rel, (_, line) in sorted(values.items()):
                findings.append(Finding(
                    rel, line, "field-modulus-mismatch",
                    f"{label} field modulus disagrees across sources: "
                    f"{detail} — verification on one side will accept "
                    "what the other rejects"))
    return findings

"""graftlint wire/constants cross-checker.

The Python sidecar (``sidecar/protocol.py``) and the C++ node
(``native/src/crypto/sidecar_client.cpp``) speak a hand-rolled binary
protocol, and the curve arithmetic keeps its field moduli duplicated
between the device ops, the host reference implementations, and (as
documentation constants) the C++ crypto layer.  No test exercises both
sides of every constant — a one-sided edit ships a node that corrupts
QCs on the wire.  This pass parses both trees (AST for Python, regex for
the C++ — clang-free by design) and asserts they agree.

Rules:
  wire-tag-mismatch       sidecar opcode values differ (or are missing)
                          between protocol.py and sidecar_client.cpp
  wire-length-mismatch    fixed record sizes differ: digest, Ed25519
                          pk/sig, BLS pk/sig/sk byte lengths
  wire-header-mismatch    the header field layout drifted: protocol.py's
                          ``struct`` format strings (_HDR / _REPLY_HDR)
                          no longer match the byte sequence
                          ``write_header`` emits (or the reply-rid
                          offsets the C++ reader parses)
  field-modulus-mismatch  the 2^255-19 / BLS12-381 field modulus
                          literals disagree across ops/field25519.py,
                          utils/intmath.py, ops/field381.py,
                          offchain/bls12381.py and crypto.hpp
  txframe-mismatch        the graftingress signed-tx frame drifted
                          between crypto/txsign.py and
                          native/src/mempool/tx_frame.hpp: layout
                          constants (version, field lengths, payload
                          bounds, markers) or the domain-separator /
                          ingress-ctx tag strings disagree — one side
                          signs preimages the other cannot verify
  certframe-mismatch      the graftdag BatchCertificate frame drifted
                          between analysis/dagwire.py and
                          native/src/mempool/messages.hpp: the ACK tag,
                          the "dagack" signing domain, the per-vote
                          byte bound, or the MempoolMessage::Kind enum
                          values disagree — Python tooling would parse
                          (or forge in tests) ACKs the node rejects, or
                          the ACK digest recipe stops folding the
                          domain separator and batch ACKs become
                          replayable as consensus votes
"""

from __future__ import annotations

import os
import re

from .common import Finding, module_int_constants, parse_source, \
    read_source

# (python constant in protocol.py, C++ constant in sidecar_client.cpp)
_TAG_PAIRS = (
    ("OP_VERIFY_BATCH", "kOpVerifyBatch"),
    ("OP_BLS_VERIFY_AGG", "kOpBlsVerifyAgg"),
    ("OP_BLS_SIGN", "kOpBlsSign"),
    ("OP_BLS_VERIFY_VOTES", "kOpBlsVerifyVotes"),
    ("OP_BLS_VERIFY_MULTI", "kOpBlsVerifyMulti"),
    # protocol v2 (verifysched): class-tagged bulk verifies + telemetry,
    # and the version constant itself — a bump on one side only means the
    # other side was not audited for the layout change that caused it.
    ("OP_VERIFY_BULK", "kOpVerifyBulk"),
    ("OP_STATS", "kOpStats"),
    # protocol v3 (graftchaos): the sidecar fault-injection hook.
    ("OP_CHAOS", "kOpChaos"),
    # protocol v4 (graftsurge): the reply-only BUSY/retry-after opcode.
    ("OP_BUSY", "kOpBusy"),
    # protocol v6 (graftfleet): the HELLO tenant/version handshake.
    ("OP_HELLO", "kOpHello"),
    ("PROTOCOL_VERSION", "kProtocolVersion"),
)

_LEN_PAIRS = (
    ("BLS_PK_LEN", "kBlsPkLen"),
    ("BLS_SIG_LEN", "kBlsSigLen"),
    ("BLS_SK_LEN", "kBlsSkLen"),
    ("DIGEST_LEN", "kDigestLen"),
    # protocol v5 (graftscope): the block-digest context tag riding
    # between the verify header and its records.
    ("CTX_LEN", "kCtxLen"),
)

# graftingress: (python constant in crypto/txsign.py, C++ constant in
# mempool/tx_frame.hpp) — the signed-tx frame layout, pinned both sides.
_TXFRAME_INT_PAIRS = (
    ("TX_FRAME_VERSION", "kTxFrameVersion"),
    ("TX_PK_LEN", "kTxPkLen"),
    ("TX_NONCE_LEN", "kTxNonceLen"),
    ("TX_LEN_LEN", "kTxLenLen"),
    ("TX_SIG_LEN", "kTxSigLen"),
    ("TX_FRAME_HEADER_LEN", "kTxFrameHeaderLen"),
    ("TX_FRAME_OVERHEAD", "kTxFrameOverhead"),
    ("TX_MIN_PAYLOAD", "kTxMinPayload"),
    ("TX_MAX_PAYLOAD", "kTxMaxPayload"),
    ("TX_MARKER_SAMPLE", "kTxMarkerSample"),
    ("TX_MARKER_FILLER", "kTxMarkerFiller"),
    ("TX_MARKER_FORGED", "kTxMarkerForged"),
)
_TXFRAME_STR_PAIRS = (
    ("TX_SIGN_DOMAIN", "kTxSignDomain"),
    ("TX_KEY_DOMAIN", "kTxKeyDomain"),
    ("INGRESS_CTX", "kTxIngressCtxTag"),
)

# graftdag: (python constant in analysis/dagwire.py, C++ constant in
# mempool/messages.hpp) — the BatchCertificate frame, pinned both sides.
_CERTFRAME_INT_PAIRS = (
    ("BATCH_ACK_TAG", "kBatchAckTag"),
    ("BATCH_ACK_DOMAIN", "kBatchAckDomain"),
    ("CERT_VOTE_LEN", "kCertVoteLen"),
)
_CERTFRAME_KIND_PAIRS = (
    ("MEMPOOL_KIND_BATCH", "kBatch"),
    ("MEMPOOL_KIND_BATCH_REQUEST", "kBatchRequest"),
    ("MEMPOOL_KIND_ACK", "kAck"),
)

PROTOCOL = "hotstuff_tpu/sidecar/protocol.py"
SIDECAR_CLIENT = "native/src/crypto/sidecar_client.cpp"
CRYPTO_HPP = "native/src/crypto/crypto.hpp"
TXSIGN = "hotstuff_tpu/crypto/txsign.py"
TX_FRAME_HPP = "native/src/mempool/tx_frame.hpp"
DAGWIRE = "hotstuff_tpu/analysis/dagwire.py"
MEMPOOL_MSG_HPP = "native/src/mempool/messages.hpp"
FIELD25519 = "hotstuff_tpu/ops/field25519.py"
INTMATH = "hotstuff_tpu/utils/intmath.py"
FIELD381 = "hotstuff_tpu/ops/field381.py"
BLS12381 = "hotstuff_tpu/offchain/bls12381.py"


def _read(root: str, rel: str):
    path = os.path.join(root, rel)
    try:
        return read_source(path)
    except OSError:
        return None


def _line_of(source: str, pattern: str) -> int:
    m = re.search(pattern, source, re.MULTILINE)
    return source[:m.start()].count("\n") + 1 if m else 1


def cpp_int_constants(source: str) -> dict:
    """``constexpr <type> kName = <int>;`` declarations (dec or hex)."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+[\w:]+\s+(k\w+)\s*=\s*(0[xX][0-9a-fA-F']+|\d+)",
            source):
        out[m.group(1)] = int(m.group(2).replace("'", ""), 0)
    return out


def cpp_hex_string_constants(source: str) -> dict:
    """``constexpr char kName[] = "hex" "hex"...;`` -> int value."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+char\s+(k\w+)\[\]\s*=\s*(?://[^\n]*)?"
            r"((?:\s*\"[0-9a-fA-F]*\")+)",
            source):
        digits = "".join(re.findall(r'"([0-9a-fA-F]*)"', m.group(2)))
        if digits:
            out[m.group(1)] = int(digits, 16)
    return out


def cpp_shift_constants(source: str) -> dict:
    """``constexpr <type> kName = N << S;`` declarations -> value (the
    form kTxMaxPayload uses; cpp_int_constants only takes literals)."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+[\w:]+\s+(k\w+)\s*=\s*(\d+)[uUlL]*\s*<<\s*(\d+)",
            source):
        out[m.group(1)] = int(m.group(2)) << int(m.group(3))
    return out


def cpp_static_assert_values(source: str) -> dict:
    """``static_assert(kName == N, ...)`` equality pins -> {name: N} —
    how tx_frame.hpp anchors its derived header/overhead sums to
    literal byte counts a cross-checker can read."""
    out = {}
    for m in re.finditer(r"static_assert\(\s*(k\w+)\s*==\s*(\d+)", source):
        out[m.group(1)] = int(m.group(2))
    return out


def cpp_char_string_constants(source: str) -> dict:
    """``constexpr char kName[] = "text";`` declarations -> text."""
    out = {}
    for m in re.finditer(
            r"constexpr\s+char\s+(k\w+)\[\]\s*=\s*\"([^\"]*)\"", source):
        out[m.group(1)] = m.group(2)
    return out


def py_bytes_constants(source: str) -> dict:
    """Top-level ``NAME = b"..."`` assignments -> decoded text."""
    import ast

    out = {}
    tree = parse_source(source)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, bytes):
            out[node.targets[0].id] = node.value.value.decode(
                "latin-1")
    return out


def cpp_typed_enum_constants(source: str, enum: str) -> dict:
    """``enum class <enum> : <type> { kA = 0, kB = 1, ... };`` ->
    {name: value}.  Only explicitly typed enums match (messages.hpp has
    an untyped ConsensusMempoolMessage::Kind the rule must not grab);
    enumerators without an explicit value are numbered from the
    previous one."""
    m = re.search(r"enum\s+class\s+%s\s*:\s*\w+\s*\{([^}]*)\}"
                  % re.escape(enum), source)
    if not m:
        return {}
    out, nxt = {}, 0
    for part in m.group(1).split(","):
        em = re.match(r"\s*(k\w+)\s*(?:=\s*(\d+))?", part)
        if not em:
            continue
        val = int(em.group(2)) if em.group(2) else nxt
        out[em.group(1)] = val
        nxt = val + 1
    return out


def cpp_struct_array_len(source: str, struct: str) -> int | None:
    """Byte length of ``std::array<uint8_t, N> data`` inside a struct."""
    m = re.search(r"struct\s+%s\b.*?std::array<uint8_t,\s*(\d+)>\s+data"
                  % re.escape(struct), source, re.DOTALL)
    return int(m.group(1)) if m else None


_STRUCT_WIDTHS = {"B": 1, "b": 1, "H": 2, "h": 2, "I": 4, "i": 4,
                  "Q": 8, "q": 8, "x": 1}


def py_struct_formats(source: str) -> dict:
    """Top-level ``NAME = struct.Struct("fmt")`` assignments -> {name:
    (fmt string, line)} (AST; no imports)."""
    import ast

    out = {}
    tree = parse_source(source)
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "Struct" \
                and call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            out[node.targets[0].id] = (call.args[0].value, node.lineno)
    return out


def struct_fmt_fields(fmt: str):
    """"<BIIH" -> (is_little_endian, [1, 4, 4, 2]).

    Handles repeat counts ("2I" -> two 4-byte fields) and byte-string /
    pad codes ("16s"/"4x" -> one 16-/4-byte field), so a layout-identical
    format rewrite never trips the rule; an unknown code yields a None
    width (flagged by the caller)."""
    le = fmt[:1] == "<"
    body = fmt[1:] if fmt[:1] in "<>!=@" else fmt
    widths = []
    for m in re.finditer(r"(\d*)(.)", body):
        count = int(m.group(1)) if m.group(1) else 1
        ch = m.group(2)
        if ch.isspace():
            continue
        if ch in ("s", "p", "x"):
            widths.append(count)  # one count-byte field
        else:
            widths.extend([_STRUCT_WIDTHS.get(ch)] * count)
    return le, widths


def cpp_write_header_widths(source: str):
    """Byte widths of the Writer calls inside ``write_header``, in
    order: [1, 4, 4, 1, 1] for u8/u32/u32/u8/u8.  None when the function
    body is not found."""
    m = re.search(r"void\s+write_header\s*\([^)]*\)\s*\{(.*?)\n\}",
                  source, re.DOTALL)
    if not m:
        return None
    return [int(b) // 8
            for b in re.findall(r"w->u(8|16|32|64)\(", m.group(1))]


def header_layouts_match(py_widths, cpp_widths) -> bool:
    """Greedy coalescing compare: consecutive C++ writes may add up to
    one wider Python field (the u16-as-two-u8 idiom in write_header)."""
    if any(w is None for w in py_widths):
        return False
    i = 0
    for want in py_widths:
        got = 0
        while got < want and i < len(cpp_widths):
            got += cpp_widths[i]
            i += 1
        if got != want:
            return False
    return i == len(cpp_widths)


def cpp_signature_lens(source: str) -> set:
    """The wire lengths Signature::deserialize accepts."""
    m = re.search(r"data\.size\(\)\s*!=\s*(\d+)\s*&&\s*"
                  r"s\.data\.size\(\)\s*!=\s*(\d+)", source)
    if not m:
        return set()
    return {int(m.group(1)), int(m.group(2))}


def check(root: str) -> list:
    findings: list[Finding] = []

    def miss(path, rule, what):
        findings.append(Finding(path, 1, rule, f"{what} not found — the "
                                "cross-check cannot anchor; fix the "
                                "source or update wirecheck.py"))

    proto_src = _read(root, PROTOCOL)
    client_src = _read(root, SIDECAR_CLIENT)
    crypto_src = _read(root, CRYPTO_HPP)
    if proto_src is None or client_src is None or crypto_src is None:
        for rel, src in ((PROTOCOL, proto_src), (SIDECAR_CLIENT, client_src),
                         (CRYPTO_HPP, crypto_src)):
            if src is None:
                miss(rel, "wire-tag-mismatch", "source file")
        return findings

    py = module_int_constants(proto_src, PROTOCOL)
    cpp = cpp_int_constants(client_src)
    cpp.update(cpp_int_constants(crypto_src))

    # -- message tags ------------------------------------------------------
    for py_name, cpp_name in _TAG_PAIRS:
        if py_name not in py:
            miss(PROTOCOL, "wire-tag-mismatch", f"constant {py_name}")
        elif cpp_name not in cpp:
            miss(SIDECAR_CLIENT, "wire-tag-mismatch", f"constant {cpp_name}")
        elif py[py_name] != cpp[cpp_name]:
            findings.append(Finding(
                SIDECAR_CLIENT, _line_of(client_src, cpp_name),
                "wire-tag-mismatch",
                f"{cpp_name}={cpp[cpp_name]} but {PROTOCOL} "
                f"{py_name}={py[py_name]}: the node and the sidecar "
                "disagree on a message opcode"))

    # -- fixed byte lengths ------------------------------------------------
    for py_name, cpp_name in _LEN_PAIRS:
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif cpp_name not in cpp:
            miss(SIDECAR_CLIENT, "wire-length-mismatch",
                 f"constant {cpp_name}")
        elif py[py_name] != cpp[cpp_name]:
            findings.append(Finding(
                SIDECAR_CLIENT, _line_of(client_src, cpp_name),
                "wire-length-mismatch",
                f"{cpp_name}={cpp[cpp_name]} but {PROTOCOL} "
                f"{py_name}={py[py_name]}: record framing will desync"))

    digest_len = cpp_struct_array_len(crypto_src, "Digest")
    pk_len = cpp_struct_array_len(crypto_src, "PublicKey")
    sig_lens = cpp_signature_lens(crypto_src)
    checks = (
        ("DIGEST_LEN", digest_len, "struct Digest byte length"),
        ("ED_PK_LEN", pk_len, "struct PublicKey byte length"),
    )
    for py_name, cpp_val, what in checks:
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif cpp_val is None:
            miss(CRYPTO_HPP, "wire-length-mismatch", what)
        elif py[py_name] != cpp_val:
            findings.append(Finding(
                CRYPTO_HPP, _line_of(crypto_src, "struct " + (
                    "Digest" if py_name == "DIGEST_LEN" else "PublicKey")),
                "wire-length-mismatch",
                f"{what} is {cpp_val} but {PROTOCOL} "
                f"{py_name}={py[py_name]}"))
    for py_name, lens_needed in (("ED_SIG_LEN", sig_lens),
                                 ("BLS_SIG_LEN", sig_lens)):
        if py_name not in py:
            miss(PROTOCOL, "wire-length-mismatch", f"constant {py_name}")
        elif not lens_needed:
            miss(CRYPTO_HPP, "wire-length-mismatch",
                 "Signature::deserialize length check")
        elif py[py_name] not in lens_needed:
            findings.append(Finding(
                CRYPTO_HPP, _line_of(crypto_src, "bad signature length"),
                "wire-length-mismatch",
                f"Signature::deserialize accepts {sorted(lens_needed)} "
                f"but {PROTOCOL} {py_name}={py[py_name]}"))

    # -- header layouts ----------------------------------------------------
    fmts = py_struct_formats(proto_src)
    if "_HDR" not in fmts:
        miss(PROTOCOL, "wire-header-mismatch", "_HDR struct format")
    else:
        fmt, line = fmts["_HDR"]
        le, widths = struct_fmt_fields(fmt)
        if not le:
            findings.append(Finding(
                PROTOCOL, line, "wire-header-mismatch",
                f"_HDR format {fmt!r} is not explicit little-endian "
                "('<'): the C++ Writer emits LE; native byte order "
                "silently desyncs on a BE host"))
        cpp_widths = cpp_write_header_widths(client_src)
        if cpp_widths is None:
            miss(SIDECAR_CLIENT, "wire-header-mismatch",
                 "write_header body")
        elif not header_layouts_match(widths, cpp_widths):
            findings.append(Finding(
                SIDECAR_CLIENT, _line_of(client_src,
                                         r"void\s+write_header"),
                "wire-header-mismatch",
                f"write_header emits byte widths {cpp_widths} but "
                f"{PROTOCOL} _HDR={fmt!r} parses {widths}: every "
                "request frame desyncs after the header"))
    if "_REPLY_HDR" not in fmts:
        miss(PROTOCOL, "wire-header-mismatch", "_REPLY_HDR struct format")
    else:
        fmt, line = fmts["_REPLY_HDR"]
        le, widths = struct_fmt_fields(fmt)
        if not le:
            findings.append(Finding(
                PROTOCOL, line, "wire-header-mismatch",
                f"_REPLY_HDR format {fmt!r} is not explicit "
                "little-endian ('<')"))
        # The C++ reader routes replies by the request id it parses at
        # raw byte offsets (reader_loop_): opcode then rid.
        if len(widths) >= 2 and None not in widths[:2] and \
                widths[1] == 4:
            off = widths[0]
            rid_ok = bool(re.search(rf"reply\[{off}\]\)", client_src)) \
                and all(re.search(
                    rf"reply\[{off + k}\]\)\s*<<\s*{8 * k}\b",
                    client_src) for k in (1, 2, 3))
            m = re.search(r"reply\.size\(\)\s*>=\s*(\d+)", client_src)
            size_ok = bool(m) and int(m.group(1)) == off + 4
            if not (rid_ok and size_ok):
                findings.append(Finding(
                    SIDECAR_CLIENT,
                    _line_of(client_src, r"reply\.size\(\)"),
                    "wire-header-mismatch",
                    f"reader_loop_ parses the reply request id at a "
                    f"layout that does not match {PROTOCOL} "
                    f"_REPLY_HDR={fmt!r} (rid at offset {off}, 4 bytes "
                    "LE): replies would be routed to the wrong pending "
                    "request"))
        else:
            findings.append(Finding(
                PROTOCOL, line, "wire-header-mismatch",
                f"_REPLY_HDR={fmt!r} no longer starts with a 1-byte "
                "opcode and 4-byte request id; update reader_loop_'s "
                "raw-offset parse and this check together"))

    # -- field moduli ------------------------------------------------------
    hexes = cpp_hex_string_constants(crypto_src)
    moduli = {
        "P25519": (
            "kEd25519FieldPrimeHex",
            [(FIELD25519, "P"), (INTMATH, "P")],
        ),
        "Q381": (
            "kBls381FieldPrimeHex",
            [(FIELD381, "Q"), (BLS12381, "Q")],
        ),
    }
    for label, (cpp_name, py_sites) in moduli.items():
        values = {}
        for rel, const in py_sites:
            src = _read(root, rel)
            if src is None:
                miss(rel, "field-modulus-mismatch", "source file")
                continue
            consts = module_int_constants(src, rel)
            if const not in consts:
                miss(rel, "field-modulus-mismatch", f"constant {const}")
                continue
            values[rel] = (consts[const], _line_of(src, rf"^{const}\s*="))
        if cpp_name not in hexes:
            miss(CRYPTO_HPP, "field-modulus-mismatch",
                 f"constant {cpp_name}")
        else:
            values[CRYPTO_HPP] = (hexes[cpp_name],
                                  _line_of(crypto_src, cpp_name))
        if len({v for v, _ in values.values()}) > 1:
            detail = "; ".join(f"{rel} has {hex(v)[:18]}..."
                               for rel, (v, _) in sorted(values.items()))
            for rel, (_, line) in sorted(values.items()):
                findings.append(Finding(
                    rel, line, "field-modulus-mismatch",
                    f"{label} field modulus disagrees across sources: "
                    f"{detail} — verification on one side will accept "
                    "what the other rejects"))

    # -- graftingress signed-tx frame --------------------------------------
    txsign_src = _read(root, TXSIGN)
    txframe_src = _read(root, TX_FRAME_HPP)
    if txsign_src is None or txframe_src is None:
        for rel, src in ((TXSIGN, txsign_src), (TX_FRAME_HPP, txframe_src)):
            if src is None:
                miss(rel, "txframe-mismatch", "source file")
        return findings
    tx_py = module_int_constants(txsign_src, TXSIGN)
    tx_cpp = cpp_int_constants(txframe_src)
    tx_cpp.update(cpp_shift_constants(txframe_src))
    # Derived sums (header/overhead) are pinned by static_asserts — the
    # literal the assert names is the cross-checkable value.
    tx_cpp.update(cpp_static_assert_values(txframe_src))
    for py_name, cpp_name in _TXFRAME_INT_PAIRS:
        if py_name not in tx_py:
            miss(TXSIGN, "txframe-mismatch", f"constant {py_name}")
        elif cpp_name not in tx_cpp:
            miss(TX_FRAME_HPP, "txframe-mismatch", f"constant {cpp_name}")
        elif tx_py[py_name] != tx_cpp[cpp_name]:
            findings.append(Finding(
                TX_FRAME_HPP, _line_of(txframe_src, cpp_name),
                "txframe-mismatch",
                f"{cpp_name}={tx_cpp[cpp_name]} but {TXSIGN} "
                f"{py_name}={tx_py[py_name]}: client frames desync "
                "against admission parsing"))
    tx_py_str = py_bytes_constants(txsign_src)
    tx_cpp_str = cpp_char_string_constants(txframe_src)
    for py_name, cpp_name in _TXFRAME_STR_PAIRS:
        if py_name not in tx_py_str:
            miss(TXSIGN, "txframe-mismatch", f"bytes constant {py_name}")
        elif cpp_name not in tx_cpp_str:
            miss(TX_FRAME_HPP, "txframe-mismatch", f"constant {cpp_name}")
        elif tx_py_str[py_name] != tx_cpp_str[cpp_name]:
            findings.append(Finding(
                TX_FRAME_HPP, _line_of(txframe_src, cpp_name),
                "txframe-mismatch",
                f"{cpp_name}={tx_cpp_str[cpp_name]!r} but {TXSIGN} "
                f"{py_name}={tx_py_str[py_name]!r}: domain-separated "
                "preimages (or the ingress ctx tag) diverge — one side "
                "signs what the other cannot verify"))

    # -- graftdag BatchCertificate frame -----------------------------------
    dag_src = _read(root, DAGWIRE)
    mmsg_src = _read(root, MEMPOOL_MSG_HPP)
    if dag_src is None or mmsg_src is None:
        for rel, src in ((DAGWIRE, dag_src), (MEMPOOL_MSG_HPP, mmsg_src)):
            if src is None:
                miss(rel, "certframe-mismatch", "source file")
        return findings
    dag_py = module_int_constants(dag_src, DAGWIRE)
    dag_cpp = cpp_int_constants(mmsg_src)
    dag_cpp.update(cpp_typed_enum_constants(mmsg_src, "Kind"))
    for py_name, cpp_name in (_CERTFRAME_INT_PAIRS
                              + _CERTFRAME_KIND_PAIRS):
        if py_name not in dag_py:
            miss(DAGWIRE, "certframe-mismatch", f"constant {py_name}")
        elif cpp_name not in dag_cpp:
            miss(MEMPOOL_MSG_HPP, "certframe-mismatch",
                 f"constant {cpp_name}")
        elif dag_py[py_name] != dag_cpp[cpp_name]:
            findings.append(Finding(
                MEMPOOL_MSG_HPP, _line_of(mmsg_src, cpp_name),
                "certframe-mismatch",
                f"{cpp_name}={dag_cpp[cpp_name]} but {DAGWIRE} "
                f"{py_name}={dag_py[py_name]}: certificate frames "
                "desync between the node and Python tooling"))
    # The ACK rides the MempoolMessage Kind field: the standalone tag
    # constant must stay equal to the enum value it aliases.
    if {"kBatchAckTag", "kAck"} <= dag_cpp.keys() \
            and dag_cpp["kBatchAckTag"] != dag_cpp["kAck"]:
        findings.append(Finding(
            MEMPOOL_MSG_HPP, _line_of(mmsg_src, "kBatchAckTag"),
            "certframe-mismatch",
            f"kBatchAckTag={dag_cpp['kBatchAckTag']} but "
            f"MempoolMessage::Kind::kAck={dag_cpp['kAck']}: the signed "
            "ACK no longer rides the Kind tag it claims to"))
    # Semantic pin: make_ack must still fold the domain separator into
    # the signed digest — without it a batch ACK is a signature over a
    # bare batch digest and becomes replayable in other contexts.
    if not re.search(r"update_u64_le\(\s*kBatchAckDomain\s*\)", mmsg_src):
        findings.append(Finding(
            MEMPOOL_MSG_HPP, _line_of(mmsg_src, "kBatchAckDomain"),
            "certframe-mismatch",
            "no update_u64_le(kBatchAckDomain) in the ACK digest "
            "assembly: the domain separator is declared but no longer "
            "folded into what ACKs sign — dagwire.ack_digest() and the "
            "node now disagree on the preimage"))
    return findings

"""grafttaint C++ extractor: the native-tree half of the taint checker.

Builds the same ``TaintFn`` records the Python extractor produces, from
the brace/lexer machinery the cxxsync checker already proved out
(``_strip`` blanks comments/strings offset-stably; ``_Blocks`` matches
braces and names function blocks).  No clang, no compilation.

Vocabulary (see taint.py for the model):
  sources   ``::deserialize`` / ``recv`` / ``recv_until`` calls, plus
            the network receiver handler lambdas (``*receiver_.spawn``
            — the mempool tx/peer ingress entry points, whose bodies
            attribute to the enclosing named function by design).
  gates     ``// VERIFIES(<label>)`` immediately above a function
            definition marks the function; the same comment inside a
            body marks an inline gate point scoped to its innermost
            brace block (verdict-``ok`` branches, loopback re-entry).
  sinks     QC acceptance, TC assembly, commit, store writes, mempool
            admission — each with the gate labels it accepts.
"""

from __future__ import annotations

import re

from .cxxsync import _Blocks, _line_of, _strip, cpp_suppressed_rules

CXX_TARGETS = (
    "native/src/consensus/core.cpp",
    "native/src/consensus/consensus.cpp",
    "native/src/consensus/messages.cpp",
    "native/src/consensus/aggregator.cpp",
    "native/src/consensus/mempool_driver.cpp",
    "native/src/mempool/mempool.cpp",
    "native/src/mempool/messages.cpp",
    "native/src/mempool/processor.hpp",
    "native/src/mempool/processor.cpp",
    "native/src/mempool/quorum_waiter.cpp",
    "native/src/mempool/synchronizer.cpp",
    "native/src/mempool/ingress.hpp",
    "native/src/mempool/tx_verify.hpp",
    "native/src/mempool/tx_verify.cpp",
    "native/src/crypto/crypto.cpp",
)

CXX_SOURCE_CALLS = frozenset({
    "deserialize", "recv", "recv_until", "read_frame"})

# callee -> (sink label, acceptable gate labels)
CXX_SINKS = {
    "process_qc": ("qc-accept",
                   frozenset({"qc", "sig", "tc", "block",
                              "device-verdict"})),
    "finish_tc": ("tc-assembly",
                  frozenset({"qc", "sig", "tc", "device-verdict"})),
    "advance_round_via_tc": ("tc-assembly",
                             frozenset({"qc", "sig", "tc",
                                        "device-verdict"})),
    "commit": ("commit",
               frozenset({"qc", "sig", "tc", "block",
                          "device-verdict"})),
    "store_block": ("store-write",
                    frozenset({"qc", "sig", "tc", "block",
                               "device-verdict"})),
    "try_write": ("store-write",
                  frozenset({"batch-digest", "qc", "sig",
                             "device-verdict"})),
    "admit": ("mempool-admission", frozenset({"ingress-budget"})),
    # graftingress: the admission-verify stage may hand a wire-sourced
    # signed tx onward to the batch maker (the store-bound path) only
    # under the tx-signature gate — a forged frame reaching this sink
    # unverified is exactly the bug class the tier exists to kill.
    "forward_admitted": ("store-write", frozenset({"tx-signature"})),
    # graftdag: the cert-driven background payload fetch.  A block's
    # certificates name the replicas the fetch targets, so prefetch may
    # only fire for a block whose certificate signatures were verified —
    # the batch-certificate gate (host path via Block::check), the
    # device verdict (async sidecar path), or the block gate that
    # contains both.  An unverified block reaching this sink would let a
    # forged certificate aim Synchronize traffic at arbitrary peers.
    "prefetch": ("cert-fetch",
                 frozenset({"batch-certificate", "device-verdict",
                            "block"})),
}

_VERIFIES_RE = re.compile(r"//\s*VERIFIES\(([\w\-]+)\)")
_CALL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*\(")
_RECEIVER_SPAWN_RE = re.compile(
    r"\b\w*receiver_?\s*(?:\.|->)\s*spawn\s*\(")
# control-flow / operator keywords _CALL_RE would otherwise pick up
_NOT_CALLS = frozenset({
    "if", "while", "for", "switch", "catch", "return", "sizeof",
    "new", "delete", "throw", "static_cast", "const_cast",
    "dynamic_cast", "reinterpret_cast", "alignof", "decltype",
    "assert", "defined", "noexcept",
})
# how far below a def-level VERIFIES comment the function header may sit
_DEF_ATTACH_SPAN = 600

from .taint import Call, TaintFn  # noqa: E402  (circular-by-design)


def _named_blocks(blocks: _Blocks):
    """(start, end, name) for real function bodies — named, non-lambda."""
    return [(s, e, n) for s, e, n in blocks.ranges
            if n is not None and n != "<lambda>"]


def _owner(named, pos):
    """Innermost named function block containing ``pos`` (lambda bodies
    therefore attribute to their enclosing named function)."""
    best = None
    for s, e, _n in named:
        if s < pos < e and (best is None or e - s < best[1] - best[0]):
            best = (s, e, _n)
    return best


def extract(sources: dict) -> list:
    fns = []
    for path, src in sources.items():
        stripped = _strip(src)
        blocks = _Blocks(stripped)
        named = _named_blocks(blocks)
        by_range = {}
        for s, e, name in named:
            fn = TaintFn(name=name, path=path,
                         line=_line_of(stripped, s), language="cxx")
            by_range[(s, e)] = fn
            fns.append(fn)

        for m in _CALL_RE.finditer(stripped):
            name = m.group(1)
            if name in _NOT_CALLS:
                continue
            own = _owner(named, m.start())
            if own is None:
                continue  # declaration scope / class body, not code
            by_range[(own[0], own[1])].calls.append(Call(
                name, m.start(), _line_of(stripped, m.start())))

        for m in _RECEIVER_SPAWN_RE.finditer(stripped):
            own = _owner(named, m.start())
            if own is not None:
                by_range[(own[0], own[1])].source_points.append(
                    (m.start(), _line_of(stripped, m.start())))

        # VERIFIES annotations live in comments — scan the ORIGINAL text
        # (offsets align with the stripped text by construction).
        for m in _VERIFIES_RE.finditer(src):
            label = m.group(1)
            own = _owner(named, m.start())
            if own is not None:
                # inline gate point, scoped to the innermost brace block
                fn = by_range[(own[0], own[1])]
                fn.gate_points.append(
                    (m.start(), blocks.block_end(m.start()), label,
                     _line_of(stripped, m.start())))
                continue
            # def-level: attach to the next function header below
            cand = None
            for s, e, _n in named:
                if m.start() < s <= m.start() + _DEF_ATTACH_SPAN and \
                        (cand is None or s < cand[0]):
                    cand = (s, e)
            if cand is not None:
                fn = by_range[cand]
                fn.def_labels = fn.def_labels | {label}
    return fns


__all__ = ["CXX_TARGETS", "CXX_SOURCE_CALLS", "CXX_SINKS",
           "cpp_suppressed_rules", "extract"]

"""graftlint timing checker: ``block_until_ready`` must not be the
synchronization inside a timed region of the profiling scripts.

Through the tunneled device, ``block_until_ready()`` has been observed
returning before the program actually finishes (scripts/PROFILE.md):
a stage timed as ``t0 = perf_counter(); fn().block_until_ready();
dt = perf_counter() - t0`` under-reports by up to 1000x, and the bogus
number then drives real optimization decisions.  The repo convention is
to force a device->host copy (``np.asarray(out)``) as the fence —
the data dependency cannot lie.  This rule finds the anti-pattern
mechanically in the profiling/experiment scripts.

Rule:
  block-until-ready-in-timing   a ``.block_until_ready()`` call lexically
                                inside a timed region — between the first
                                and last ``time.perf_counter()`` /
                                ``time.monotonic()`` reads of the same
                                function scope (nested functions and
                                lambdas are their own scopes, so warmup
                                fences outside the timer and helpers that
                                never time anything stay legal)

Scope model is deliberately lexical, not dataflow: a timer read before
and after a statement is what makes it "timed", and the profiling
scripts are straight-line enough that this has no false positives on
the repaired tree (fixtures in tests/test_analysis.py pin both
directions).
"""

from __future__ import annotations

import ast
import glob as _glob
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

# Profiling / experiment scripts, relative to the repo root (globs
# allowed): the scripts whose printed numbers feed optimization
# decisions.  bench.py's timed loops synchronize via np.asarray already
# and its block_until_ready uses are warmup fences; it rides along so a
# regression there fires too.
DEFAULT_TARGETS = (
    "scripts/profile_verify.py",
    "scripts/exp_*.py",
    "bench.py",
    # grafttrace: the obs package computes the numbers every future perf
    # claim cites — a bogus fence there poisons ALL attribution.
    "hotstuff_tpu/obs/*.py",
)

_TIMER_READS = {"perf_counter", "monotonic", "perf_counter_ns",
                "monotonic_ns"}


def _scopes(tree: ast.Module):
    """Yield (scope node, direct statements/expressions) with nested
    function/lambda bodies cut out — each function times (or doesn't)
    on its own."""
    nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def direct_nodes(root):
        out = []
        stack = [iter(ast.iter_child_nodes(root))]
        while stack:
            try:
                node = next(stack[-1])
            except StopIteration:
                stack.pop()
                continue
            if isinstance(node, nested):
                continue  # its body is a separate scope
            out.append(node)
            stack.append(iter(ast.iter_child_nodes(node)))
        return out

    yield tree, direct_nodes(tree)
    for node in ast.walk(tree):
        if isinstance(node, nested):
            yield node, direct_nodes(node)


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    for _scope, nodes in _scopes(tree):
        timer_lines = []
        blockers = []
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr in _TIMER_READS:
                    timer_lines.append(node.lineno)
                elif func.attr == "block_until_ready":
                    blockers.append(node)
            elif isinstance(func, ast.Name) and func.id in _TIMER_READS:
                timer_lines.append(node.lineno)
        if len(timer_lines) < 2:
            continue
        lo, hi = min(timer_lines), max(timer_lines)
        for node in blockers:
            if lo < node.lineno < hi:
                findings.append(Finding(
                    path, node.lineno, "block-until-ready-in-timing",
                    "block_until_ready() inside a timed region: through "
                    "the tunneled device it can return before the program "
                    "finishes (PROFILE.md: under-reports by ~1000x); "
                    "fence with a forced D2H copy — np.asarray(out) — "
                    "instead"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        for path in sorted(_glob.glob(os.path.join(root, target))):
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

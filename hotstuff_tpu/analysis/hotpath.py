"""graftlint hot-path checker: AST lint for the JAX device modules.

The headline claim (device-accelerated ``QC::verify``) lives on a JAX hot
path that degrades *silently*: a stray ``int(x)`` inside a jitted verify
program is a blocking host-device round trip per launch, a Python branch
on a traced value is a retrace (or a crash) per distinct input, a bare
float literal quietly promotes int32 limb math, and an undonated packed
buffer doubles device-memory pressure on the tunneled chip.  None of
these break a unit test — throughput just sags.  This pass finds them
mechanically.

Model: "hot" code is the jit closure — functions reachable from a jit /
pjit / shard_map / in-hot ``lax.scan`` root, following calls (including
across the scanned modules via ``from . import field25519 as F`` style
aliases) that pass at least one *tainted* (traced) argument.  Parameters
annotated as python scalars (``int``/``bool``/``str``/``bytes``) or with
literal defaults are static configuration, not traced values.  Taint is
laundered by static attributes (``.shape``/``.dtype``/``.ndim``/
``.size``) and ``len``, which is what keeps shape arithmetic legal.

Rules (see analysis/README.md):
  host-sync-in-jit     int()/float()/bool()/.item()/np.asarray() on a
                       traced value inside hot code
  traced-branch        if/while/assert/ternary on a traced value
  mutable-default-arg  dict/list/set default on a hot function parameter
  f64-literal          float literal meeting a traced value in hot code
                       (f64 promotion), or an explicit float64 dtype
  implicit-limb-dtype  jnp.array/np.array/jnp.asarray of a literal limb
                       list without an explicit dtype in hot code
  nondonated-buffer    jax.jit of a verify_* entry point without
                       donate_argnums (the verify loop hands each packed
                       buffer to the device exactly once)
"""

from __future__ import annotations

import ast
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

# Paths scanned by default, relative to the repo root.
#
# crypto/eddsa.py and offchain/bls12381.py joined the set with the
# verifysched PR: eddsa is the dispatch layer the engine's hot loop calls
# straight into (its helpers are one refactor away from being pulled
# inside a jit closure — the cross-module taint walk keeps that honest),
# and bls12381 is the host BLS reference the device module's jit bodies
# call for constants/decoding, where a traced value leaking in would be
# a silent per-launch host sync.  sidecar/sched is control-plane code
# for the engine thread itself; scanning it keeps device-touching
# helpers from accreting there unchecked (lint_gate pins each module
# with --must-cover).
DEFAULT_TARGETS = (
    "hotstuff_tpu/ops",
    # graftkern: the ops/ scan is non-recursive (os.listdir), so the
    # Pallas kernel subpackage must be its own target — every kernel
    # body is jit-reachable device code where a stray host sync or an
    # implicit dtype is the exact silent-degradation class this scan
    # exists for (lint_gate pins each module with --must-cover).
    "hotstuff_tpu/ops/kern",
    "hotstuff_tpu/parallel",
    "hotstuff_tpu/sidecar/service.py",
    "hotstuff_tpu/sidecar/ring.py",
    "hotstuff_tpu/sidecar/sched",
    "hotstuff_tpu/crypto/eddsa.py",
    "hotstuff_tpu/offchain/bls12381.py",
)

_LAUNDER_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "sharding"}
_STATIC_ANNOTATIONS = {"int", "bool", "str", "bytes", "float"}
_HOST_CASTS = {"int", "float", "bool"}
_UNTAINTED_CALLS = {"len", "range", "enumerate", "zip", "isinstance",
                    "type", "hasattr", "getattr", "divmod", "min", "max"}
_SCAN_HOFS = {("lax", "scan"), ("lax", "fori_loop"), ("lax", "while_loop"),
              ("lax", "map"), ("jax", "vmap"), ("jax", "pmap")}


def _attr_chain(node):
    """a.b.c -> ["a", "b", "c"]; None when the base isn't a plain name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class _Module:
    def __init__(self, path: str, source: str):
        self.path = path
        self.name = os.path.splitext(os.path.basename(path))[0]
        self.source = source
        self.tree = parse_source(source, path)
        self.functions: dict[str, ast.FunctionDef] = {}
        # alias -> module basename, for imports of *scanned* modules
        # (``from . import field25519 as F``, ``from ..ops import ed25519``)
        self.module_aliases: dict[str, str] = {}
        self.numpy_aliases: set[str] = set()
        self.jnp_aliases: set[str] = set()
        self.jax_aliases: set[str] = set()
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.numpy_aliases.add(alias)
                    elif a.name == "jax.numpy":
                        self.jnp_aliases.add(a.asname or "jax")
                    elif a.name == "jax":
                        self.jax_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    alias = a.asname or a.name
                    if node.module == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(alias)
                    elif node.module and node.module.endswith("numpy"):
                        self.numpy_aliases.add(alias)
                    else:
                        self.module_aliases[alias] = a.name


def _static_param_names(fn: ast.FunctionDef) -> set:
    """Parameters that are static python config, not traced arrays."""
    static = set()
    args = list(fn.args.posonlyargs) + list(fn.args.args) \
        + list(fn.args.kwonlyargs)
    for a in args:
        ann = a.annotation
        if isinstance(ann, ast.Name) and ann.id in _STATIC_ANNOTATIONS:
            static.add(a.arg)
    defaults = list(fn.args.defaults)
    # defaults align with the tail of posonly+args
    pos = list(fn.args.posonlyargs) + list(fn.args.args)
    for a, d in zip(pos[len(pos) - len(defaults):], defaults):
        if isinstance(d, (ast.Constant, ast.Tuple)):
            static.add(a.arg)
    for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if isinstance(d, (ast.Constant, ast.Tuple)):
            static.add(a.arg)
    return static


def _param_names(fn: ast.FunctionDef) -> list:
    return [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs)] \
        + ([fn.args.vararg.arg] if fn.args.vararg else []) \
        + ([fn.args.kwarg.arg] if fn.args.kwarg else [])


class _FunctionPass(ast.NodeVisitor):
    """Taint walk over one hot function body."""

    def __init__(self, checker, module: _Module, fn, tainted: set):
        self.checker = checker
        self.module = module
        self.fn = fn
        self.tainted = set(tainted)
        self.local_defs = {}
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for node in body:
            if isinstance(node, ast.FunctionDef):
                self.local_defs[node.name] = node

    # -- findings ----------------------------------------------------------

    def _report(self, node, rule, message):
        self.checker.report(self.module, node, rule, message)

    # -- taint evaluation --------------------------------------------------

    def is_tainted(self, node) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _LAUNDER_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.BinOp):
            lt, rt = self.is_tainted(node.left), self.is_tainted(node.right)
            for side, other in ((node.left, rt), (node.right, lt)):
                if other and isinstance(side, ast.Constant) \
                        and isinstance(side.value, float):
                    self._report(
                        side, "f64-literal",
                        "bare float literal %r meets a traced value: "
                        "promotes integer limb math (f64 with x64 enabled); "
                        "use an explicitly-typed constant" % (side.value,))
            return lt or rt
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            if self.is_tainted(node.test):
                self._report(node, "traced-branch",
                             "ternary on a traced value inside jitted code "
                             "(concretization error or retrace); use "
                             "jnp.where / lax.select")
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_tainted(e)
                       for e in list(node.keys) + list(node.values) if e)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.JoinedStr):
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._eval_comprehension(node)
        if isinstance(node, ast.Lambda):
            return False  # handled where it is passed to a scan HOF
        return False

    def _eval_comprehension(self, node) -> bool:
        saved = set(self.tainted)
        try:
            for gen in node.generators:
                if self.is_tainted(gen.iter):
                    self._taint_target(gen.target)
                for cond in gen.ifs:
                    if self.is_tainted(cond):
                        self._report(cond, "traced-branch",
                                     "comprehension filter on a traced "
                                     "value inside jitted code")
            if isinstance(node, ast.DictComp):
                return self.is_tainted(node.key) or \
                    self.is_tainted(node.value)
            return self.is_tainted(node.elt)
        finally:
            self.tainted = saved

    def _dtype_is_f64(self, node) -> bool:
        if isinstance(node, ast.Constant) and node.value in (
                "float64", "double"):
            return True
        chain = _attr_chain(node)
        return bool(chain) and chain[-1] == "float64"

    def _eval_call(self, node: ast.Call) -> bool:
        func = node.func
        args_tainted = [self.is_tainted(a) for a in node.args] + \
                       [self.is_tainted(k.value) for k in node.keywords]
        any_tainted = any(args_tainted)

        for kw in node.keywords:
            if kw.arg == "dtype" and self._dtype_is_f64(kw.value):
                self._report(kw.value, "f64-literal",
                             "explicit float64 dtype in hot code: the "
                             "device substrate is int32/f32 limb math")

        # x.item() — the canonical blocking device->host fetch
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and self.is_tainted(func.value):
            self._report(node, "host-sync-in-jit",
                         ".item() on a traced value: blocking host-device "
                         "sync inside jitted code")
            return False

        chain = _attr_chain(func)
        if chain:
            head, tail = chain[0], chain[-1]
            # int(x) / float(x) / bool(x) on a traced value
            if len(chain) == 1 and tail in _HOST_CASTS and any_tainted:
                self._report(node, "host-sync-in-jit",
                             "%s() on a traced value: forces a host "
                             "round trip (or a concretization error) "
                             "inside jitted code" % tail)
                return False
            if len(chain) == 1 and tail in _UNTAINTED_CALLS:
                return False
            # np.asarray / np.array of a device value
            if head in self.module.numpy_aliases and len(chain) == 2:
                if tail in ("asarray", "array") and any_tainted:
                    self._report(node, "host-sync-in-jit",
                                 "np.%s() of a traced value: copies the "
                                 "buffer to host inside jitted code" % tail)
                    return False
                if tail == "float64":
                    self._report(node, "f64-literal",
                                 "np.float64 in hot code promotes limb "
                                 "math to f64")
            # implicit-dtype array constants
            if tail in ("array", "asarray") and len(chain) == 2 and (
                    head in self.module.numpy_aliases
                    or head in self.module.jnp_aliases):
                if node.args and isinstance(node.args[0],
                                            (ast.List, ast.Tuple)) \
                        and not any(k.arg == "dtype"
                                    for k in node.keywords):
                    self._report(
                        node, "implicit-limb-dtype",
                        "%s.%s of a literal constant list without an "
                        "explicit dtype: relies on default promotion "
                        "(int32 vs int64/f64 differs across backends); "
                        "pass dtype=jnp.int32/uint32 explicitly"
                        % (head, tail))
            # scan-style higher-order fns: their body fn is hot with all
            # params tainted
            if len(chain) >= 2 and (chain[-2], tail) in _SCAN_HOFS \
                    and node.args:
                self._mark_callable_hot(node.args[0])
            if tail == "shard_map" and node.args:
                self._mark_callable_hot(node.args[0])

        # propagate into module-local / cross-module callees
        self._register_call(func, node, args_tainted)

        if isinstance(func, ast.Attribute):
            # method call on a tainted object (x.reshape(...), x.astype(..))
            if self.is_tainted(func.value):
                return True
        return any_tainted

    def _mark_callable_hot(self, arg):
        if isinstance(arg, ast.Lambda):
            sub = _FunctionPass(self.checker, self.module, arg,
                                {a.arg for a in arg.args.args})
            sub.is_tainted(arg.body)
            return
        if isinstance(arg, ast.Name):
            target = self.local_defs.get(arg.id) or \
                self.module.functions.get(arg.id)
            if target is not None:
                tainted = set(_param_names(target)) - \
                    _static_param_names(target)
                self.checker.analyze_local(self.module, target, tainted)

    def _register_call(self, func, node: ast.Call, args_tainted):
        """Taint the callee's parameters when a traced value flows in."""
        if not any(args_tainted):
            return
        target_module, target = None, None
        if isinstance(func, ast.Name):
            target = self.local_defs.get(func.id) or \
                self.module.functions.get(func.id)
            target_module = self.module
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            mod_name = self.module.module_aliases.get(func.value.id)
            target_module = self.checker.modules_by_name.get(mod_name)
            if target_module is not None:
                target = target_module.functions.get(func.attr)
        if target is None or target_module is None:
            return
        params = _param_names(target)
        static = _static_param_names(target)
        tainted = set()
        for i, a in enumerate(node.args):
            if i < len(params) and args_tainted[i]:
                tainted.add(params[i])
        for kw, t in zip(node.keywords,
                         args_tainted[len(node.args):]):
            if kw.arg and t:
                tainted.add(kw.arg)
        tainted -= static
        if tainted:
            if target.name in target_module.functions:
                self.checker.enqueue(target_module, target.name, tainted)
            else:  # nested def: analyze inline
                self.checker.analyze_local(target_module, target, tainted)

    # -- statements --------------------------------------------------------

    def _taint_target(self, target):
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def run(self):
        if isinstance(self.fn, ast.Lambda):
            self.is_tainted(self.fn.body)
            return
        # two passes so loop-carried assignments converge
        for _ in range(2):
            before = set(self.tainted)
            for stmt in self.fn.body:
                self.visit(stmt)
            if self.tainted == before:
                break

    def visit_FunctionDef(self, node):
        # nested defs are analyzed when they flow into a scan/shard_map or
        # are called with tainted args; check their defaults here
        self.checker.check_defaults(self.module, node, hot=False)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if self.is_tainted(node.value):
            for t in node.targets:
                self._taint_target(t)
        else:
            for t in node.targets:
                self.generic_untaint(t)

    def generic_untaint(self, target):
        if isinstance(target, ast.Name):
            self.tainted.discard(target.id)

    def visit_AnnAssign(self, node):
        if node.value is not None and self.is_tainted(node.value):
            self._taint_target(node.target)

    def visit_AugAssign(self, node):
        if self.is_tainted(node.value):
            self._taint_target(node.target)
        elif isinstance(node.target, ast.Name) and \
                node.target.id in self.tainted:
            # tainted op= untainted stays tainted; still check f64 meet
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, float):
                self._report(node.value, "f64-literal",
                             "bare float literal meets a traced value "
                             "(augmented assign)")

    def visit_If(self, node):
        if self.is_tainted(node.test):
            self._report(node, "traced-branch",
                         "python branch on a traced value inside jitted "
                         "code: concretization error or per-value retrace; "
                         "use jnp.where / lax.cond")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node):
        if self.is_tainted(node.test):
            self._report(node, "traced-branch",
                         "while on a traced value inside jitted code; use "
                         "lax.while_loop")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Assert(self, node):
        if self.is_tainted(node.test):
            self._report(node, "traced-branch",
                         "assert on a traced value inside jitted code "
                         "(concretization error); fold into the result "
                         "mask or use checkify")

    def visit_For(self, node):
        if self.is_tainted(node.iter):
            self._taint_target(node.target)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Return(self, node):
        if node.value is not None:
            self.is_tainted(node.value)

    def visit_Expr(self, node):
        self.is_tainted(node.value)

    def visit_Try(self, node):
        # except-handler bodies are statements too — ast.ExceptHandler is
        # neither expr nor stmt, so the generic walk below would skip
        # them and hide violations in error paths.
        for stmt in node.body + node.orelse + node.finalbody:
            self.visit(stmt)
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)

    def generic_visit(self, node):
        # evaluate any expressions hanging off statements we don't model
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.is_tainted(child)
            elif isinstance(child, ast.stmt):
                self.visit(child)


class HotPathChecker:
    def __init__(self, sources: dict):
        """sources: path -> python source text."""
        self.modules = {p: _Module(p, s) for p, s in sources.items()}
        self.modules_by_name = {m.name: m for m in self.modules.values()}
        self.findings: list[Finding] = []
        self._seen_findings: set = set()
        self._processed: dict = {}   # (module path, fn name) -> tainted set
        self._queue: list = []

    # -- reporting ---------------------------------------------------------

    def report(self, module: _Module, node, rule: str, message: str):
        key = (module.path, node.lineno, rule)
        if key in self._seen_findings:
            return
        self._seen_findings.add(key)
        self.findings.append(
            Finding(module.path, node.lineno, rule, message))

    def check_defaults(self, module: _Module, fn, hot: bool):
        if isinstance(fn, ast.Lambda):
            return
        if not hot:
            return
        for d in list(fn.args.defaults) + \
                [d for d in fn.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.Dict, ast.List, ast.Set)):
                self.report(module, d, "mutable-default-arg",
                            "mutable default argument on a jit-reachable "
                            "function: unhashable as a static arg and a "
                            "retrace/aliasing hazard; default to None")

    # -- scheduling --------------------------------------------------------

    def enqueue(self, module: _Module, fn_name: str, tainted: set):
        key = (module.path, fn_name)
        already = self._processed.get(key, set())
        if tainted <= already:
            return
        self._processed[key] = already | tainted
        self._queue.append((module, module.functions[fn_name],
                            already | tainted))

    def analyze_local(self, module: _Module, fn, tainted: set):
        """Analyze a nested def / lambda right away (no global name)."""
        key = (module.path, id(fn))
        already = self._processed.get(key, set())
        if tainted <= already:
            return
        self._processed[key] = already | tainted
        self.check_defaults(module, fn, hot=True)
        _FunctionPass(self, module, fn, already | tainted).run()

    # -- roots -------------------------------------------------------------

    def _jit_roots(self, module: _Module):
        """Enqueue jit/pjit/shard_map roots with their traced params."""
        for fn in module.functions.values():
            for dec in fn.decorator_list:
                if self._is_jit_expr(module, dec):
                    static = self._static_argnames(dec, fn)
                    tainted = set(_param_names(fn)) - \
                        _static_param_names(fn) - static
                    self.enqueue(module, fn.name, tainted)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            tail = chain[-1]
            if tail in ("jit", "pjit") and node.args:
                self._root_from_arg(module, node, node.args[0])
            elif tail == "shard_map" and node.args:
                self._root_from_arg(module, node, node.args[0])

    def _root_from_arg(self, module: _Module, call: ast.Call, arg):
        static = set()
        fn = None
        if isinstance(arg, ast.Name):
            fn = module.functions.get(arg.id)
        elif isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name):
            # shard_map(make_body(...)) factory pattern: the factory's
            # nested defs are the hot bodies
            factory = module.functions.get(arg.func.id)
            if factory is not None:
                for stmt in ast.walk(factory):
                    if isinstance(stmt, ast.FunctionDef) and \
                            stmt is not factory:
                        tainted = set(_param_names(stmt)) - \
                            _static_param_names(stmt)
                        self.analyze_local(module, stmt, tainted)
            return
        if fn is None:
            return
        static = self._static_argnames(call, fn)
        tainted = set(_param_names(fn)) - _static_param_names(fn) - static
        self.enqueue(module, fn.name, tainted)

    def _is_jit_expr(self, module: _Module, node) -> bool:
        chain = _attr_chain(node)
        if chain and chain[-1] in ("jit", "pjit"):
            return True
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "partial" and node.args:
                return self._is_jit_expr(module, node.args[0])
            if chain and chain[-1] in ("jit", "pjit"):
                return True
        return False

    @staticmethod
    def _static_argnames(call, fn) -> set:
        """Params excluded from tracing via static_argnums/static_argnames."""
        if not isinstance(call, ast.Call):
            return set()
        params = _param_names(fn)
        out = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                vals = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                for v in vals:
                    if isinstance(v, ast.Constant):
                        out.add(str(v.value))
            elif kw.arg == "static_argnums":
                vals = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                for v in vals:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, int) and \
                            v.value < len(params):
                        out.add(params[v.value])
        return out

    # -- donation rule -----------------------------------------------------

    def _check_donation(self, module: _Module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] != "jit":
                continue
            if not (len(chain) == 1 or
                    chain[0] in module.jax_aliases):
                continue
            if not node.args:
                continue
            target = _attr_chain(node.args[0])
            if not target or not target[-1].startswith("verify"):
                continue
            kwargs = {kw.arg for kw in node.keywords}
            if None in kwargs or kwargs & {"donate_argnums",
                                           "donate_argnames"}:
                continue
            self.report(
                module, node, "nondonated-buffer",
                "jax.jit(%s) without donate_argnums: the verify loop "
                "hands each packed buffer to the device exactly once, so "
                "not donating it doubles device-memory pressure per "
                "launch; donate arg 0 (or suppress with a rationale if "
                "the caller re-times a device-resident input)"
                % target[-1])

    # -- driver ------------------------------------------------------------

    def run(self) -> list:
        for module in self.modules.values():
            self._check_donation(module)
            self._jit_roots(module)
        while self._queue:
            module, fn, tainted = self._queue.pop()
            self.check_defaults(module, fn, hot=True)
            _FunctionPass(self, module, fn, tainted).run()
        sources = {m.path: m.source for m in self.modules.values()}
        return sorted(apply_suppressions(self.findings, sources),
                      key=lambda f: (f.path, f.line))


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    return HotPathChecker(sources).run()


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    """Lint the repo's hot-path files under ``root``."""
    sources = {}
    for target in targets:
        path = os.path.join(root, target)
        if os.path.isfile(path):
            files = [path]
        elif os.path.isdir(path):
            files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(".py"))
        else:
            continue
        for f in files:
            sources[os.path.relpath(f, root)] = read_source(f)
    return check_sources(sources)

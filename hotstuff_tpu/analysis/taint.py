"""grafttaint checker: whole-program verification-gate provenance.

Proves (lexically, clang-free, import-free) that **no unverified wire
bytes reach a consensus sink** — in both the Python sidecar and the C++
node.  Three vocabularies drive it:

  sources     where untrusted bytes enter: socket/unix reads and wire
              deserialization (``read_frame``/``recv`` in the sidecar,
              ``::deserialize``/``recv_until`` and the network
              ``*receiver_.spawn`` handlers in the native tree).
  sanitizers  verification gates, DECLARED in the code itself:
              ``// VERIFIES(<label>)`` on (or above) a C++ function
              definition marks that function as a gate; the same comment
              INSIDE a body marks a gate point whose scope is the
              enclosing brace block (for verdict-``ok`` checks and
              loopback re-entry facts).  Python uses
              ``# graftlint: sanitizes=<label>`` with the same two
              positions (def line / body line).
  sinks       where acceptance becomes irreversible: QC acceptance
              (``process_qc``), TC assembly (``finish_tc`` /
              ``advance_round_via_tc``), commit, block-store writes,
              mempool admission (``admit``), device-launch packing
              (``VerifyEngine.submit``) and sidecar VERDICT emission
              (``encode_reply``/``encode_reply_raw`` with a non-literal
              mask).

Model: per-function taint summaries over a bare-name call graph.  Taint
enters a body at its wire-source points (and transitively: a call to a
function that reads the wire is itself a source point) and at function
entry when some caller passes tainted data.  A gate call — or an inline
gate point — sanitizes every lexically later position in scope with its
label.  Entry states meet across call sites: a function is
*entry-verified* only when EVERY tainted call edge into it carries at
least one gate label (labels union; one ungated edge collapses the
state, which is what the mutation fixtures exercise).  Each sink accepts
a specific label set — e.g. ``commit`` accepts ``qc``/``device-verdict``
but not ``frame-structure`` — so parsing alone can never stand in for
signature verification.

Rules:
  unverified-flow-to-sink  wire-tainted data reaches a sink with no
                           acceptable gate label on the path
  unreachable-sanitizer    a declared gate is never called anywhere in
                           the scanned tree (the classic deleted-verify
                           mutation)
  unannotated-gate         a ``verify*``-shaped function is called on a
                           tainted path but its definition carries no
                           gate annotation — the analysis cannot credit
                           what the author did not declare

Soundness limits (deliberate, documented): the call graph is bare-name
and lexical — callbacks passed as values (the sidecar reply closures,
channel handoffs) are not edges, a gate call gates later positions even
when its result is ignored, and C++ lambdas attribute their calls to the
enclosing named function (which is exactly right for the network
receiver handlers).  ``results/taintmap.json`` records every PROVEN
wire→gate→sink path so the gate coverage is auditable, not just the
absence of findings.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

from .common import Finding, parse_source, read_source, suppressed_rules


# ---------------------------------------------------------------------------
# Shared model (taintcxx builds the same records from the native tree)
# ---------------------------------------------------------------------------

@dataclass
class Call:
    callee: str
    pos: int      # comparable intra-function position (char or line*1e6+col)
    line: int
    exempt: bool = False   # never classified as a sink (literal-mask replies)


@dataclass
class TaintFn:
    name: str
    path: str
    line: int
    language: str  # "py" | "cxx"
    calls: list = field(default_factory=list)
    # extractor-detected wire entries beyond source-named calls
    # (the C++ ``*receiver_.spawn`` handler lambdas): [(pos, line)]
    source_points: list = field(default_factory=list)
    # inline gate points: [(pos, scope_end_pos|None, label, line)]
    gate_points: list = field(default_factory=list)
    # non-empty => this function IS a declared gate
    def_labels: frozenset = frozenset()


from . import taintcxx  # noqa: E402  (needs Call/TaintFn defined above)


PY_TARGETS = (
    "hotstuff_tpu/sidecar/protocol.py",
    "hotstuff_tpu/sidecar/service.py",
    # graftingress: the Python twin of the signed-tx frame codec — no
    # wire sources of its own, but scanned so a future recv/sink edge
    # grown here cannot dodge the gate vocabulary silently.
    "hotstuff_tpu/crypto/txsign.py",
)

DEFAULT_TARGETS = PY_TARGETS + taintcxx.CXX_TARGETS

# Written by check() (and therefore by the CLI / lint_gate) — the
# machine-readable proof of which wire→gate→sink paths exist.
MAP_OUT = os.path.join("results", "taintmap.json")

PY_SOURCE_CALLS = frozenset({"read_frame", "recv", "recv_into", "recvfrom"})

# callee -> (sink label, acceptable gate labels)
PY_SINKS = {
    "encode_reply": ("verdict-emission",
                     frozenset({"device-verdict", "sig"})),
    "encode_reply_raw": ("verdict-emission",
                         frozenset({"device-verdict", "sig"})),
    # admission into the verify engine = the device-launch pack pipeline;
    # frame-structure (decode_request's bounds/shape validation) is the
    # gate that keeps hostile lengths out of the packer.
    "submit": ("device-launch-pack", frozenset({"frame-structure"})),
}

SOURCES = {"py": PY_SOURCE_CALLS, "cxx": taintcxx.CXX_SOURCE_CALLS}
SINKS = {"py": PY_SINKS, "cxx": taintcxx.CXX_SINKS}

VERIFY_SHAPE = re.compile(r"^_?verify")

_SANITIZES_RE = re.compile(r"#\s*graftlint:\s*sanitizes=([\w\-]+)")

_LINE_POS = 10 ** 6  # python positions: line * _LINE_POS + col


# ---------------------------------------------------------------------------
# Python extraction
# ---------------------------------------------------------------------------

def _is_literal(node) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(isinstance(e, ast.Constant) for e in node.elts)
    return False


class _PyCalls(ast.NodeVisitor):
    """Calls of one function body; nested defs are skipped entirely (their
    bodies run later via callbacks the name graph cannot see)."""

    def __init__(self):
        self.calls: list[Call] = []
        self.nested: list[tuple[int, int]] = []

    def visit_FunctionDef(self, node):
        self.nested.append((node.lineno, node.end_lineno or node.lineno))

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name:
            exempt = False
            if name in ("encode_reply", "encode_reply_raw") and \
                    len(node.args) >= 3 and _is_literal(node.args[2]):
                exempt = True  # literal mask (PING/CHAOS echo), no verdict
            if name == "encode_reply_raw" and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id == "OP_HELLO":
                # Handshake echo (protocol v6): an OP_HELLO-tagged reply
                # is routed to decode_hello_body, never read as a verify
                # mask — the body is the server version byte plus the
                # validated tenant id, not a verdict.
                exempt = True
            self.calls.append(Call(
                name, node.lineno * _LINE_POS + node.col_offset,
                node.lineno, exempt))
        self.generic_visit(node)


def _py_extract(sources: dict) -> list:
    fns = []
    for path, src in sources.items():
        tree = parse_source(src, path)
        gate_lines: dict[int, str] = {}
        for i, text in enumerate(src.splitlines(), start=1):
            m = _SANITIZES_RE.search(text)
            if m:
                gate_lines[i] = m.group(1)
        defs: list = []

        def collect(nodes):
            for n in nodes:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.append(n)
                elif isinstance(n, ast.ClassDef):
                    collect(n.body)

        collect(tree.body)
        for d in defs:
            fn = TaintFn(name=d.name, path=path, line=d.lineno,
                         language="py")
            header = {d.lineno, d.lineno - 1} | \
                {dec.lineno for dec in d.decorator_list}
            fn.def_labels = frozenset(
                gate_lines[ln] for ln in header if ln in gate_lines)
            visitor = _PyCalls()
            for stmt in d.body:
                visitor.visit(stmt)
            fn.calls = visitor.calls
            end = d.end_lineno or d.lineno
            for ln, label in gate_lines.items():
                if d.lineno < ln <= end and ln not in header and \
                        not any(a <= ln <= b for a, b in visitor.nested):
                    # inline gate point: sanitizes the rest of the body
                    fn.gate_points.append(
                        (ln * _LINE_POS + _LINE_POS - 1, None, label, ln))
            fns.append(fn)
    return fns


# ---------------------------------------------------------------------------
# Interprocedural solver (both languages)
# ---------------------------------------------------------------------------

class _Analysis:
    def __init__(self, fns: list):
        self.fns = fns
        self.registry: dict = {}
        for fn in fns:
            self.registry.setdefault((fn.language, fn.name), []).append(fn)
        self.gate_labels: dict = {}
        for fn in fns:
            if fn.def_labels:
                key = (fn.language, fn.name)
                self.gate_labels[key] = \
                    self.gate_labels.get(key, frozenset()) | fn.def_labels
        # id(fn) -> [verified: bool, labels: set] (present = entry-tainted)
        self.entry: dict = {}
        # id(fn) -> (caller fn, call line, origin str)
        self.witness: dict = {}
        self.origin: dict = {}
        self._source_closure()

    # -- sources -----------------------------------------------------------

    def _source_closure(self):
        """Effective wire-entry points per body: genuine source calls plus
        calls to any function that transitively reads the wire."""
        self.eff_sources = {id(fn): list(fn.source_points)
                            for fn in self.fns}
        is_src = {id(fn): bool(fn.source_points) for fn in self.fns}
        for fn in self.fns:
            if fn.source_points:
                self.origin[id(fn)] = \
                    f"{fn.path}:{fn.source_points[0][1]}"
        changed = True
        while changed:
            changed = False
            for fn in self.fns:
                have = {p for p, _ in self.eff_sources[id(fn)]}
                for c in fn.calls:
                    direct = c.callee in SOURCES[fn.language]
                    via = next(
                        (t for t in self.registry.get(
                            (fn.language, c.callee), ()) if is_src[id(t)]),
                        None)
                    if (direct or via is not None) and c.pos not in have:
                        self.eff_sources[id(fn)].append((c.pos, c.line))
                        have.add(c.pos)
                        is_src[id(fn)] = True
                        self.origin.setdefault(
                            id(fn),
                            f"{fn.path}:{c.line}" if direct
                            else self.origin.get(
                                id(via), f"{fn.path}:{c.line}"))
                        changed = True
        for pts in self.eff_sources.values():
            pts.sort(key=lambda t: t[0])

    # -- state queries -----------------------------------------------------

    def _gates_before(self, fn, start, pos) -> set:
        """Gate labels active at ``pos``: inline gate points and gate-fn
        calls after ``start`` (None = function entry) and before ``pos``,
        whose scope still covers ``pos``."""
        out: set = set()
        for gpos, gend, label, _ln in fn.gate_points:
            if (start is None or gpos > start) and gpos < pos and \
                    (gend is None or pos <= gend):
                out.add(label)
        for c in fn.calls:
            if (start is None or c.pos > start) and c.pos < pos:
                labels = self.gate_labels.get((fn.language, c.callee))
                if labels:
                    out |= labels
        return out

    def _contexts(self, fn, pos) -> list:
        """Live taints at ``pos``: [(gate labels, origin)] — one entry for
        in-body wire taint (from the LAST source point before pos), one
        for entry taint.  Empty list = position unreachable by taint."""
        ctxs = []
        before = [s for s in self.eff_sources[id(fn)] if s[0] < pos]
        if before:
            ctxs.append((
                frozenset(self._gates_before(fn, before[-1][0], pos)),
                self.origin.get(id(fn), f"{fn.path}:{fn.line}")))
        ent = self.entry.get(id(fn))
        if ent is not None:
            base = set(ent[1]) if ent[0] else set()
            w = self.witness.get(id(fn))
            ctxs.append((
                frozenset(base | self._gates_before(fn, None, pos)),
                w[2] if w else f"{fn.path}:{fn.line}"))
        return ctxs

    # -- fixpoint ----------------------------------------------------------

    def propagate(self):
        changed, iters = True, 0
        while changed and iters < 64:
            changed, iters = False, iters + 1
            for fn in self.fns:
                for c in sorted(fn.calls, key=lambda c: c.pos):
                    ctxs = self._contexts(fn, c.pos)
                    if not ctxs:
                        continue
                    for tgt in self.registry.get(
                            (fn.language, c.callee), ()):
                        if tgt is fn:
                            continue
                        for labels, origin in ctxs:
                            verified = bool(labels)
                            ent = self.entry.get(id(tgt))
                            if ent is None:
                                self.entry[id(tgt)] = \
                                    [verified, set(labels)]
                                self.witness[id(tgt)] = \
                                    (fn, c.line, origin)
                                changed = True
                            else:
                                nv = ent[0] and verified
                                nl = ent[1] | labels
                                if nv != ent[0] or nl != ent[1]:
                                    ent[0], ent[1] = nv, nl
                                    changed = True

    # -- reporting ---------------------------------------------------------

    def _chain(self, fn) -> list:
        chain, seen, cur = [fn.name], {id(fn)}, fn
        while True:
            w = self.witness.get(id(cur))
            if not w or id(w[0]) in seen:
                break
            cur = w[0]
            chain.append(cur.name)
            seen.add(id(cur))
        chain.reverse()
        return chain

    def report(self):
        findings, paths = [], []
        called = {(fn.language, c.callee)
                  for fn in self.fns for c in fn.calls}
        for fn in self.fns:
            if fn.def_labels and (fn.language, fn.name) not in called:
                findings.append(Finding(
                    fn.path, fn.line, "unreachable-sanitizer",
                    f"sanitizer '{fn.name}' "
                    f"(VERIFIES {', '.join(sorted(fn.def_labels))}) is "
                    f"never called anywhere in the scanned tree: the gate "
                    f"it declares protects nothing — wire the call back "
                    f"in or retire the annotation"))
        for fn in self.fns:
            for c in sorted(fn.calls, key=lambda c: c.pos):
                ctxs = self._contexts(fn, c.pos)
                if not ctxs:
                    continue
                cfg = SINKS[fn.language].get(c.callee)
                if cfg and not c.exempt:
                    label, accepted = cfg
                    self_gate = self.gate_labels.get(
                        (fn.language, c.callee), frozenset())
                    for labels, origin in ctxs:
                        eff = labels | self_gate
                        if eff & accepted:
                            paths.append({
                                "language": fn.language, "sink": label,
                                "call": c.callee, "file": fn.path,
                                "line": c.line,
                                "gates": sorted(eff & accepted),
                                "source": origin,
                                "via": self._chain(fn)})
                        else:
                            findings.append(Finding(
                                fn.path, c.line, "unverified-flow-to-sink",
                                f"wire-tainted data reaches {label} sink "
                                f"'{c.callee}' with no acceptable "
                                f"verification gate on the path (needs "
                                f"one of: "
                                f"{', '.join(sorted(accepted))}; saw: "
                                f"{', '.join(sorted(eff)) or 'none'}; "
                                f"taint from {origin})"))
                if VERIFY_SHAPE.match(c.callee) and \
                        not self.gate_labels.get(
                            (fn.language, c.callee)):
                    tgts = self.registry.get((fn.language, c.callee), ())
                    if tgts:
                        findings.append(Finding(
                            fn.path, c.line, "unannotated-gate",
                            f"verification-shaped call '{c.callee}' on a "
                            f"wire-tainted path, but its definition "
                            f"({tgts[0].path}:{tgts[0].line}) carries no "
                            f"VERIFIES/sanitizes annotation: declare the "
                            f"gate's label so the taint analysis can "
                            f"credit it (or rename it if it does not "
                            f"verify anything)"))
        seen, unique = set(), []
        for f in findings:
            key = (f.path, f.line, f.rule)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        seen, upaths = set(), []
        for p in paths:
            key = (p["language"], p["sink"], p["file"], p["line"])
            if key not in seen:
                seen.add(key)
                upaths.append(p)
        return unique, upaths


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def analyze_sources(py_sources: dict, cxx_sources: dict):
    """Lint {relpath: source} mappings for both languages.  Returns
    ``(findings, mapdoc)`` where mapdoc is the taintmap document."""
    fns = _py_extract(py_sources) + taintcxx.extract(cxx_sources)
    an = _Analysis(fns)
    an.propagate()
    findings, paths = an.report()
    # inline suppressions, same contract as every other checker
    py_sup = {p: suppressed_rules(s) for p, s in py_sources.items()}
    cxx_sup = {p: taintcxx.cpp_suppressed_rules(s)
               for p, s in cxx_sources.items()}
    kept = []
    for f in findings:
        sup = py_sup.get(f.path) or cxx_sup.get(f.path) or {}
        if f.rule in sup.get(f.line, ()):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    paths.sort(key=lambda p: (p["sink"], p["file"], p["line"]))
    coverage: dict = {}
    for p in paths:
        coverage[p["sink"]] = coverage.get(p["sink"], 0) + 1
    mapdoc = {
        "schema": "grafttaint-map-v1",
        "clean": not kept,
        "gates": sorted(
            [{"name": fn.name, "file": fn.path, "line": fn.line,
              "labels": sorted(fn.def_labels)}
             for fn in fns if fn.def_labels],
            key=lambda g: (g["file"], g["line"])),
        "sinks_covered": coverage,
        "paths": paths,
    }
    return kept, mapdoc


def check_sources(py_sources: dict, cxx_sources: dict | None = None) -> list:
    """Unit-test entry point; findings only."""
    return analyze_sources(py_sources, cxx_sources or {})[0]


def check(root: str, targets=DEFAULT_TARGETS, map_out=None) -> list:
    """Lint the repo under ``root``.  When ``map_out`` is set (the CLI
    passes MAP_OUT), the proven wire→gate→sink paths are written there as
    the auditable coverage artifact."""
    py_sources, cxx_sources = {}, {}
    for rel in targets:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        (py_sources if rel.endswith(".py") else cxx_sources)[rel] = \
            read_source(path)
    findings, mapdoc = analyze_sources(py_sources, cxx_sources)
    if map_out:
        out = os.path.join(root, map_out)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w", encoding="utf-8") as fh:
            json.dump(mapdoc, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return findings

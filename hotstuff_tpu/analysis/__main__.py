"""graftlint CLI: ``python -m hotstuff_tpu.analysis [options]``.

Runs the hot-path lint, the wire/constants cross-checker, and the
sanitizer-wiring check; prints one line per finding and exits non-zero
when anything fires.  ``scripts/lint_gate.py`` is the CI entry point.
"""

from __future__ import annotations

import argparse
import os
import sys

CHECKERS = ("hotpath", "wire", "sanitize")


def run_all(root: str, checkers=CHECKERS) -> list:
    from . import hotpath, sanitize, wirecheck

    findings = []
    if "hotpath" in checkers:
        findings += hotpath.check(root)
    if "wire" in checkers:
        findings += wirecheck.check(root)
    if "sanitize" in checkers:
        findings += sanitize.check(root)
    # checkers may anchor the same missing constant from two rule paths
    seen, unique = set(), []
    for f in findings:
        key = (f.path, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def _default_root() -> str:
    # hotstuff_tpu/analysis/__main__.py -> repo root
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hotstuff_tpu.analysis",
        description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_default_root(),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--checker", action="append", choices=CHECKERS,
                    help="run only this checker (repeatable; default all)")
    args = ap.parse_args(argv)
    checkers = tuple(args.checker) if args.checker else CHECKERS
    findings = run_all(args.root, checkers)
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    if findings:
        print(f"graftlint: {len(findings)} finding(s) "
              f"[checkers: {', '.join(checkers)}]", file=sys.stderr)
        return 1
    print(f"graftlint: clean [checkers: {', '.join(checkers)}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())

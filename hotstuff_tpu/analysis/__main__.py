"""graftlint CLI: ``python -m hotstuff_tpu.analysis [options]``.

Runs every registered checker (hot path, wire, sanitizer wiring, launch
shapes, timing fences, socket bounds, trace spans, thread discipline,
C++ lock discipline, verification-gate taint provenance); prints one
line per finding — or the
``graftlint-findings-v1`` JSON document under ``--json``/``--json-out``
— and exits non-zero when anything fires.  ``scripts/lint_gate.py`` is
the CI entry point.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys

CHECKERS = ("hotpath", "wire", "sanitize", "padshape", "timing", "sockets",
            "obsspan", "obsgrammar", "threads", "cxxsync", "ingress",
            "guard", "ring", "taint", "tenantq")


def run_all(root: str, checkers=CHECKERS) -> list:
    from . import cxxsync, guardlint, hotpath, ingress, obsgrammar, \
        obsspan, padshape, ringlint, sanitize, sockets, taint, \
        tenantlint, threads, timing, wirecheck

    findings = []
    if "hotpath" in checkers:
        findings += hotpath.check(root)
    if "wire" in checkers:
        findings += wirecheck.check(root)
    if "sanitize" in checkers:
        findings += sanitize.check(root)
    if "padshape" in checkers:
        findings += padshape.check(root)
    if "timing" in checkers:
        findings += timing.check(root)
    if "sockets" in checkers:
        findings += sockets.check(root)
    if "obsspan" in checkers:
        findings += obsspan.check(root)
    if "obsgrammar" in checkers:
        findings += obsgrammar.check(root)
    if "threads" in checkers:
        findings += threads.check(root)
    if "cxxsync" in checkers:
        findings += cxxsync.check(root)
    if "ingress" in checkers:
        findings += ingress.check(root)
    if "guard" in checkers:
        findings += guardlint.check(root)
    if "ring" in checkers:
        findings += ringlint.check(root)
    if "taint" in checkers:
        # CLI runs refresh the wire→gate→sink proof artifact alongside
        # the findings (tests call taint.check() directly, no write)
        findings += taint.check(root, map_out=taint.MAP_OUT)
    if "tenantq" in checkers:
        findings += tenantlint.check(root)
    # checkers may anchor the same missing constant from two rule paths
    seen, unique = set(), []
    for f in findings:
        key = (f.path, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def check_coverage(root: str, must_cover) -> list:
    """Assert each repo-relative file exists and is scanned — the gate
    for 'this new module MUST be linted' requirements.

    A pin may be checker-qualified (``hotpath:path``, ``sockets:path``,
    ``timing:path``, ``padshape:path``, ``threads:path``,
    ``cxxsync:path``) to demand coverage by THAT
    checker's target set: a device module pinned to hotpath stays
    covered-by-hotpath even though the sockets checker happens to scan
    the same directory (a union would let the hot-path scan silently
    lose a file another checker's prefix still matches).  A bare path
    accepts any checker.  scripts/lint_gate.py pins the RLC scalar
    module and the verifysched modules to hotpath, and the graftchaos
    modules to sockets."""
    from . import cxxsync, guardlint, hotpath, ingress, obsgrammar, \
        obsspan, padshape, ringlint, sockets, taint, tenantlint, \
        threads, timing
    from .common import Finding

    target_sets = {
        "hotpath": tuple(hotpath.DEFAULT_TARGETS),
        "sockets": tuple(sockets.DEFAULT_TARGETS),
        "timing": tuple(timing.DEFAULT_TARGETS),
        "padshape": tuple(padshape.DEFAULT_TARGETS),
        "obsspan": tuple(obsspan.DEFAULT_TARGETS),
        "obsgrammar": tuple(obsgrammar.DEFAULT_TARGETS),
        "threads": tuple(threads.DEFAULT_TARGETS),
        "cxxsync": tuple(cxxsync.DEFAULT_TARGETS),
        "ingress": tuple(ingress.DEFAULT_TARGETS),
        "guard": tuple(guardlint.DEFAULT_TARGETS),
        "ring": tuple(ringlint.DEFAULT_TARGETS),
        "taint": tuple(taint.DEFAULT_TARGETS),
        "tenantq": tuple(tenantlint.DEFAULT_TARGETS),
    }
    findings = []
    for pin in must_cover:
        checker, _, rel = pin.rpartition(":")
        if checker and checker not in target_sets:
            findings.append(Finding(
                rel or pin, 1, "must-cover",
                f"unknown checker {checker!r} in --must-cover pin "
                f"(have {', '.join(sorted(target_sets))})"))
            continue
        scan_targets = target_sets[checker] if checker else tuple(
            t for ts in target_sets.values() for t in ts)
        norm = rel.replace(os.sep, "/")
        if not os.path.isfile(os.path.join(root, rel)):
            findings.append(Finding(
                rel, 1, "must-cover",
                "required module is missing from the tree"))
            continue
        # Targets are files, directories, or globs (timing's
        # "scripts/exp_*.py"); a pin matches any of the three shapes.
        covered = any(
            norm == t or norm.startswith(t.rstrip("/") + "/")
            or fnmatch.fnmatch(norm, t)
            for t in scan_targets)
        if not covered:
            where = f"the {checker} scan targets" if checker \
                else "every lint scan target"
            findings.append(Finding(
                rel, 1, "must-cover",
                f"file is outside {where} "
                f"({', '.join(scan_targets)}); add it to the checker's "
                "DEFAULT_TARGETS or move it"))
    return findings


def _default_root() -> str:
    # hotstuff_tpu/analysis/__main__.py -> repo root
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hotstuff_tpu.analysis",
        description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_default_root(),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--checker", action="append", choices=CHECKERS,
                    help="run only this checker (repeatable; default all)")
    ap.add_argument("--must-cover", action="append",
                    metavar="[CHECKER:]RELPATH",
                    help="fail unless this repo-relative file exists AND "
                         "lies inside a lint scan target — of the named "
                         "checker (hotpath/sockets) when qualified, of "
                         "any checker when bare (guards against a module "
                         "silently escaping its lint; repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="print machine-readable findings JSON to stdout "
                         "instead of one line per finding (exit status "
                         "unchanged: 0 clean, 1 findings)")
    ap.add_argument("--json-out", default=None, metavar="PATH",
                    help="additionally write the findings JSON to PATH "
                         "(CI artifact; text output stays on stdout)")
    args = ap.parse_args(argv)
    checkers = tuple(args.checker) if args.checker else CHECKERS
    findings = run_all(args.root, checkers)
    findings += check_coverage(args.root, args.must_cover or ())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json or args.json_out:
        doc = findings_json(findings, checkers)
        if args.json_out:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
                fh.write("\n")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if not findings:
            print(f"graftlint: clean [checkers: {', '.join(checkers)}]")
    if findings:
        print(f"graftlint: {len(findings)} finding(s) "
              f"[checkers: {', '.join(checkers)}]", file=sys.stderr)
        return 1
    return 0


def findings_json(findings, checkers) -> dict:
    """The machine-readable findings document (``--json``/``--json-out``):
    CI and future tooling consume this instead of scraping the text
    renderer, so the schema is part of the gate's contract — additive
    changes only."""
    return {
        "schema": "graftlint-findings-v1",
        "checkers": list(checkers),
        "clean": not findings,
        "findings": [
            {"rule": f.rule, "file": f.path, "line": f.line,
             "evidence": f.message}
            for f in findings
        ],
    }


if __name__ == "__main__":
    sys.exit(main())

"""graftlint CLI: ``python -m hotstuff_tpu.analysis [options]``.

Runs the hot-path lint, the wire/constants cross-checker, and the
sanitizer-wiring check; prints one line per finding and exits non-zero
when anything fires.  ``scripts/lint_gate.py`` is the CI entry point.
"""

from __future__ import annotations

import argparse
import os
import sys

CHECKERS = ("hotpath", "wire", "sanitize", "padshape", "timing")


def run_all(root: str, checkers=CHECKERS) -> list:
    from . import hotpath, padshape, sanitize, timing, wirecheck

    findings = []
    if "hotpath" in checkers:
        findings += hotpath.check(root)
    if "wire" in checkers:
        findings += wirecheck.check(root)
    if "sanitize" in checkers:
        findings += sanitize.check(root)
    if "padshape" in checkers:
        findings += padshape.check(root)
    if "timing" in checkers:
        findings += timing.check(root)
    # checkers may anchor the same missing constant from two rule paths
    seen, unique = set(), []
    for f in findings:
        key = (f.path, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def check_coverage(root: str, must_cover) -> list:
    """Assert each repo-relative file exists and is scanned by the
    hot-path checker's target set — the gate for 'this new device module
    MUST be linted' requirements (scripts/lint_gate.py pins the RLC
    scalar module this way)."""
    from . import hotpath
    from .common import Finding

    findings = []
    for rel in must_cover:
        norm = rel.replace(os.sep, "/")
        if not os.path.isfile(os.path.join(root, rel)):
            findings.append(Finding(
                rel, 1, "must-cover",
                "required module is missing from the tree"))
            continue
        covered = any(
            norm == t or norm.startswith(t.rstrip("/") + "/")
            for t in hotpath.DEFAULT_TARGETS)
        if not covered:
            findings.append(Finding(
                rel, 1, "must-cover",
                "file is outside the hotpath scan targets "
                f"({', '.join(hotpath.DEFAULT_TARGETS)}); add it to "
                "hotpath.DEFAULT_TARGETS or move it"))
    return findings


def _default_root() -> str:
    # hotstuff_tpu/analysis/__main__.py -> repo root
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hotstuff_tpu.analysis",
        description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_default_root(),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--checker", action="append", choices=CHECKERS,
                    help="run only this checker (repeatable; default all)")
    ap.add_argument("--must-cover", action="append", metavar="RELPATH",
                    help="fail unless this repo-relative file exists AND "
                         "lies inside a hotpath scan target (guards "
                         "against a new device module silently escaping "
                         "the lint; repeatable)")
    args = ap.parse_args(argv)
    checkers = tuple(args.checker) if args.checker else CHECKERS
    findings = run_all(args.root, checkers)
    findings += check_coverage(args.root, args.must_cover or ())
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    if findings:
        print(f"graftlint: {len(findings)} finding(s) "
              f"[checkers: {', '.join(checkers)}]", file=sys.stderr)
        return 1
    print(f"graftlint: clean [checkers: {', '.join(checkers)}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""graftdag wire constants — Python mirror of the certified-batch
mempool frame layout in ``native/src/mempool/messages.hpp``.

The C++ node is the authority for what travels on the wire; this module
re-declares the BatchCertificate constants so Python tooling (the Twins
log analyzer, bench post-processing, tests) can parse and synthesize
ACK digests without linking the native tree.  Every constant here is
pinned against its ``k``-prefixed twin by the graftlint wire
cross-checker (``wirecheck.py`` certframe rule) — edit BOTH sides or
the lint gate fails.
"""

from __future__ import annotations

import hashlib

# MempoolMessage::Kind tag values (enum class Kind : uint32_t).
MEMPOOL_KIND_BATCH = 0
MEMPOOL_KIND_BATCH_REQUEST = 1
MEMPOOL_KIND_ACK = 2

# kBatchAckTag: the MempoolMessage tag of a signed batch ACK — must stay
# equal to MEMPOOL_KIND_ACK (the ACK rides the same Kind field).
BATCH_ACK_TAG = 2

# kBatchAckDomain: domain-separation constant folded into the digest an
# ACK signs, so a batch-availability signature can never be replayed as
# a consensus vote (little-endian bytes spell "dagack").
BATCH_ACK_DOMAIN = 0x6B6361676164

# kCertVoteLen: minimum serialized bytes per certificate vote record —
# a 32-byte Ed25519 public key plus a 64-byte signature, the same
# per-element bound QC::deserialize uses.
ED_PK_LEN = 32
ED_SIG_LEN = 64
CERT_VOTE_LEN = ED_PK_LEN + ED_SIG_LEN

DIGEST_LEN = 32


def ack_digest(batch_digest: bytes) -> bytes:
    """The 32-byte digest every batch ACK signs: SHA-512 truncated to
    32 bytes over ``batch_digest || BATCH_ACK_DOMAIN`` (8-byte LE) —
    bit-identical to ``BatchAck digest`` assembly in messages.hpp."""
    if len(batch_digest) != DIGEST_LEN:
        raise ValueError(
            f"batch digest must be {DIGEST_LEN} bytes, "
            f"got {len(batch_digest)}")
    h = hashlib.sha512()
    h.update(batch_digest)
    h.update(BATCH_ACK_DOMAIN.to_bytes(8, "little"))
    return h.digest()[:DIGEST_LEN]

"""graftlint obsgrammar checker: the Python<->C++ log-line grammar pins.

graftscope's node-side observability rests on two FROZEN log grammars
emitted by the C++ node and mined by Python regexes:

  * ``TRACE stage=<s> block=<digest> round=<r>`` — consensus/core.cpp
    ``trace_stage`` -> ``obs/trace.py _NODE_TRACE_RE``;
  * ``METRICS commits=<n> commit_rate=<f> ingress_tx=<n>
    ingress_bytes=<n> busy=<n> breaker=<state>`` — common/metrics.cpp
    ``emit_sample`` -> ``obs/sampler.py _NODE_METRICS_RE``.

Nothing type-checks the pair: a C++ edit that renames or reorders a
key ships a node whose telemetry silently stops parsing — the join
rate drops to zero, the replica series vanishes, and every downstream
perf note degrades without a single test failing.  This checker holds
the two sides together mechanically, wirecheck-style (AST-free regex
over the C++, string constants over the Python):

Rules:
  trace-grammar-mismatch    the ordered ``key=`` token list mined from
                            the Python TRACE regex no longer matches
                            the string literals of the C++ emit site
                            (or either side's anchor is missing)
  metrics-grammar-mismatch  same, for the METRICS line

The comparison is ORDERED and prefix-anchored: the Python miners are
``re.findall`` over ``<LEADER> key1=.. key2=..``, so a reordered or
renamed key on either side is a real break even when the key SET is
unchanged.  New keys may be appended on the C++ side only together
with the Python regex (append-only grammar, the log.hpp contract).
"""

from __future__ import annotations

import os
import re

from .common import Finding, read_source

TRACE_PY = "hotstuff_tpu/obs/trace.py"
METRICS_PY = "hotstuff_tpu/obs/sampler.py"
TRACE_CPP = "native/src/consensus/core.cpp"
METRICS_CPP = "native/src/common/metrics.cpp"


def _line_of(source: str, pattern: str) -> int:
    m = re.search(pattern, source, re.MULTILINE)
    return source[:m.start()].count("\n") + 1 if m else 1


def py_grammar_tokens(source: str, const_name: str):
    """``_NODE_*_RE = (r"..." r"...")`` -> ``(leader, [keys], line)`` or
    None.  The miner regexes are implicitly-concatenated raw-string
    constants; the payload is everything after the log-prefix ``\\] ``
    group, whose first word is the leader (TRACE/METRICS) and whose
    ``key=`` tokens are the grammar."""
    m = re.search(
        rf"^{re.escape(const_name)}\s*=\s*\(((?:\s*r?\"[^\"]*\")+)\)",
        source, re.MULTILINE)
    if not m:
        return None
    pattern = "".join(re.findall(r"r?\"([^\"]*)\"", m.group(1)))
    payload = pattern.split(r"\] ", 1)
    if len(payload) != 2:
        return None
    payload = payload[1]
    leader = re.match(r"(\w+) ", payload)
    keys = re.findall(r"(\w+)=", payload)
    if leader is None or not keys:
        return None
    return leader.group(1), keys, _line_of(source, re.escape(const_name))


def cpp_emit_tokens(source: str, leader: str):
    """String literals of the ``<< "LEADER key=" << ... << " key="``
    stream chain that emits the line -> ``(leader, [keys], line)`` or
    None.  The chain is anchored on the literal starting with the
    leader word and followed through consecutive ``<<`` operands;
    literals contribute their ``key=`` tokens in order."""
    anchor = re.search(rf"\"{leader} (\w+)=", source)
    if not anchor:
        return None
    # From the anchor to the statement's terminating semicolon: every
    # string literal in the << chain carries zero or more "key=" tokens.
    stmt_end = source.find(";", anchor.start())
    stmt = source[anchor.start():stmt_end if stmt_end != -1 else None]
    keys = []
    for lit in re.findall(r"\"([^\"]*)\"", stmt):
        keys.extend(re.findall(r"(\w+)=", lit))
    if not keys:
        return None
    return leader, keys, source[:anchor.start()].count("\n") + 1


def _check_pair(findings, rule, py_rel, py_src, const_name,
                cpp_rel, cpp_src, leader):
    def miss(path, what):
        findings.append(Finding(
            path, 1, rule, f"{what} not found — the grammar cross-check "
            "cannot anchor; fix the source or update obsgrammar.py"))

    py = py_grammar_tokens(py_src, const_name) if py_src else None
    cpp = cpp_emit_tokens(cpp_src, leader) if cpp_src else None
    if py_src is None:
        miss(py_rel, "source file")
    elif py is None:
        miss(py_rel, f"miner regex {const_name}")
    if cpp_src is None:
        miss(cpp_rel, "source file")
    elif cpp is None:
        miss(cpp_rel, f"'{leader} <key>=' emit site")
    if py is None or cpp is None:
        return
    py_leader, py_keys, py_line = py
    _, cpp_keys, cpp_line = cpp
    if py_leader != leader:
        findings.append(Finding(
            py_rel, py_line, rule,
            f"{const_name} mines leader {py_leader!r} but the frozen "
            f"grammar is {leader!r}"))
        return
    if py_keys != cpp_keys:
        findings.append(Finding(
            cpp_rel, cpp_line, rule,
            f"C++ emits '{leader} " + " ".join(f"{k}=.." for k in cpp_keys)
            + f"' but {py_rel} {const_name} mines keys {py_keys} — the "
            "telemetry line will silently stop parsing (the grammar is "
            "frozen append-only; change BOTH sides together)"))


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point).
    Expects the four grammar files under their repo-relative names;
    absent files simply skip their pair (fixtures test one grammar at a
    time)."""
    findings: list[Finding] = []
    norm = {p.replace(os.sep, "/"): s for p, s in sources.items()}
    if TRACE_PY in norm or TRACE_CPP in norm:
        _check_pair(findings, "trace-grammar-mismatch",
                    TRACE_PY, norm.get(TRACE_PY), "_NODE_TRACE_RE",
                    TRACE_CPP, norm.get(TRACE_CPP), "TRACE")
    if METRICS_PY in norm or METRICS_CPP in norm:
        _check_pair(findings, "metrics-grammar-mismatch",
                    METRICS_PY, norm.get(METRICS_PY), "_NODE_METRICS_RE",
                    METRICS_CPP, norm.get(METRICS_CPP), "METRICS")
    return sorted(findings, key=lambda f: (f.path, f.line))


# The four files this checker pins (must-cover target set).
DEFAULT_TARGETS = (TRACE_PY, METRICS_PY, TRACE_CPP, METRICS_CPP)


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for rel in targets:
        path = os.path.join(root, rel)
        try:
            sources[rel] = read_source(path)
        except OSError:
            sources[rel] = None
    # A missing file is reported by _check_pair, so keep the None
    # entries rather than dropping them.
    return check_sources({p: s for p, s in sources.items()})

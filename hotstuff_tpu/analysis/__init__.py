"""graftlint: repo-native static analysis for the TPU hot path, the
Python<->C++ wire protocol, and the native tree's sanitizer wiring.

Three checkers, each runnable standalone and together via
``python -m hotstuff_tpu.analysis`` (exit non-zero on findings):

* :mod:`.hotpath` — AST pass over the JAX device modules flagging
  host-device sync points, retrace hazards, dtype leaks, and non-donated
  verify-loop buffers inside jitted code.
* :mod:`.wirecheck` — cross-checks the sidecar wire constants
  (``sidecar/protocol.py``) and the shared field-modulus literals against
  the C++ node sources, so a one-sided edit fails the gate instead of
  corrupting a QC on the wire.
* :mod:`.sanitize` — asserts the ASan/UBSan/TSan build wiring
  (``native/CMakeLists.txt`` presets + ``scripts/native_sanitize.sh``)
  has not rotted; the actual sanitizer run is the tier-2 slow lane.

Suppression: a finding is silenced by ``# graftlint: disable=<rule>`` on
the finding's line or the line above (Python sources only); every
suppression should carry a rationale. See ``analysis/README.md`` for the
rule catalogue.
"""

from __future__ import annotations

from .common import Finding  # noqa: F401


def run_all(root, checkers=("hotpath", "wire", "sanitize")):
    """Run the selected checkers over a repo root; returns findings.

    Kept here (delegating to ``__main__``) so callers can use
    ``hotstuff_tpu.analysis.run_all`` without triggering the runpy
    double-import warning that a module-level ``from .__main__ import``
    would cause under ``python -m hotstuff_tpu.analysis``."""
    from .__main__ import run_all as _run

    return _run(root, checkers)

"""graftlint: repo-native static analysis for the TPU hot path, the
Python<->C++ wire protocol, launch shapes, socket bounds, trace spans,
cross-thread sharing discipline, and the native tree's sanitizer wiring.

Nine checkers, each runnable standalone and together via
``python -m hotstuff_tpu.analysis`` (exit non-zero on findings;
``--json``/``--json-out`` for machine-readable output):

* :mod:`.hotpath` — AST pass over the JAX device modules flagging
  host-device sync points, retrace hazards, dtype leaks, and non-donated
  verify-loop buffers inside jitted code.
* :mod:`.wirecheck` — cross-checks the sidecar wire constants
  (``sidecar/protocol.py``) and the shared field-modulus literals against
  the C++ node sources, so a one-sided edit fails the gate instead of
  corrupting a QC on the wire.
* :mod:`.padshape` — launch sizes must route through the bucket/shard
  helpers so no un-warmed XLA shape compiles mid-traffic.
* :mod:`.timing` — no ``block_until_ready`` inside timed regions of the
  profiling scripts (it lies through the tunneled device).
* :mod:`.sockets` — every socket/ssh operation on the process boundary
  carries an explicit bound.
* :mod:`.obsspan` — grafttrace span pairing + injected-clock discipline
  in the obs modules.
* :mod:`.threads` — graftsync Python side: cross-thread writes need one
  shared lock, daemon threads need stop flags, clock-injected thread
  loops must not read time inline.
* :mod:`.cxxsync` — graftsync C++ side: ``GUARDED_BY`` lock-discipline
  annotations enforced by a brace-scope lexer, plus explicit
  ``std::memory_order`` on every native atomic op.
* :mod:`.sanitize` — asserts the ASan/UBSan/TSan build wiring
  (``native/CMakeLists.txt`` presets + ``scripts/native_sanitize.sh`` +
  ``scripts/tsan_gate.sh``) has not rotted; the actual sanitizer runs
  are the tier-2 slow lane.

Suppression: a finding is silenced by ``# graftlint: disable=<rule>``
(Python) or ``// graftlint: disable=<rule>`` (C++ cxxsync rules) on the
finding's line or the line above; every suppression should carry a
rationale. See ``analysis/README.md`` for the rule catalogue.
"""

from __future__ import annotations

from .common import Finding  # noqa: F401


def run_all(root, checkers=None):
    """Run the selected checkers over a repo root; returns findings.

    Kept here (delegating to ``__main__``) so callers can use
    ``hotstuff_tpu.analysis.run_all`` without triggering the runpy
    double-import warning that a module-level ``from .__main__ import``
    would cause under ``python -m hotstuff_tpu.analysis``."""
    from .__main__ import CHECKERS, run_all as _run

    return _run(root, CHECKERS if checkers is None else checkers)

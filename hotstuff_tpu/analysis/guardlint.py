"""graftlint guard checker: launch supervision discipline (graftguard).

The verify engine's wedge protection rests on ONE structural invariant:
no engine-side code may block unboundedly on a staged device launch —
every dispatch/fetch future wait must route through the guard's
deadline helper (``VerifyEngine._guarded`` / ``LaunchGuard.call``), so
a hung tunneled device call becomes a declared wedge plus the
degradation ladder, never a parked engine thread with every queued
consensus verify behind it.  The type system cannot hold that
invariant; this checker holds it mechanically.

Rule:
  unsupervised-launch   an UNBOUNDED wait call — ``.result()``,
                        ``.exception()``, or ``.wait()`` with neither a
                        positional timeout nor a ``timeout=`` keyword —
                        in a guard-scanned module, outside the
                        argument subtree of a ``self._guarded(...)`` or
                        ``<...guard...>.call(...)`` call.  A bounded
                        wait (any timeout) is legal: the engine's
                        pipeline uses bounded slices precisely so
                        ``stop()`` stays observable.  Waits lexically
                        inside the thunks handed TO the guard are by
                        definition supervised (the monitor preempts
                        them), so the argument subtrees are exempt.

Worked suppressions in the real tree (both carry their evidence
inline): ``LaunchGuard.call``'s ``call.done.wait()`` — bounded by
construction, the monitor thread sets the event at every deadline
overrun — and the chaos wedge drill's deliberate
``threading.Event().wait()`` in ``VerifyEngine._guarded``, which IS the
injected hang and runs on a disposable launch thread.
"""

from __future__ import annotations

import ast
import glob as _glob
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

# The engine and the guard itself: the two modules whose blocking
# behavior decides whether a wedge hangs the sidecar.
DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar/service.py",
    "hotstuff_tpu/sidecar/guard.py",
    # graftcadence: the ring shares the engine thread, so its blocking
    # discipline is the engine's (the ring checker adds the tick-body
    # rules on top).
    "hotstuff_tpu/sidecar/ring.py",
)

_WAIT_ATTRS = {"result", "exception", "wait"}


def _is_unbounded_wait(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _WAIT_ATTRS:
        return False
    if node.args:
        return False  # positional timeout (Event.wait(t), cv.wait(t))
    if any(kw.arg == "timeout" for kw in node.keywords):
        return False
    return True


def _names_guard(node: ast.expr) -> bool:
    """True when an attribute/name chain mentions a guard (the
    ``self._guard`` receiver of ``.call``)."""
    while isinstance(node, ast.Attribute):
        if "guard" in node.attr.lower():
            return True
        node = node.value
    return isinstance(node, ast.Name) and "guard" in node.id.lower()


def _is_guard_entry(node: ast.Call) -> bool:
    """A call that supervises its argument thunks: ``self._guarded(...)``
    or ``<...guard...>.call(...)``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "_guarded":
            return True
        if func.attr == "call" and _names_guard(func.value):
            return True
    return isinstance(func, ast.Name) and func.id == "_guarded"


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    supervised: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_guard_entry(node):
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                for child in ast.walk(arg):
                    supervised.add(id(child))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in supervised:
            continue
        if _is_unbounded_wait(node):
            findings.append(Finding(
                path, node.lineno, "unsupervised-launch",
                f"unbounded .{node.func.attr}() wait outside the "
                "guard's deadline helper: a hung device call here "
                "parks the engine thread and every queued consensus "
                "verify behind it — route the wait through "
                "self._guarded(...) / LaunchGuard.call(...), or bound "
                "it with a timeout"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        for path in sorted(_glob.glob(os.path.join(root, target))):
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

"""graftlint sanitizer-wiring checker.

SURVEY.md §5.2: the reference's memory safety comes from Rust; the C++
rewrite compensates with sanitizer builds.  That only holds while the
wiring exists — a refactor that drops the CMake preset or the build
script silently un-instruments the native tree.  This pass asserts the
wiring is present and coherent; actually *running* ASan/UBSan is the
tier-2 slow lane (``scripts/native_sanitize.sh``, driven by the
slow-marked test in tests/test_analysis.py).

Rule:
  sanitizer-wiring   native/CMakeLists.txt lacks the GRAFT_SANITIZE
                     presets, or scripts/native_sanitize.sh is missing /
                     not executable / doesn't drive the sanitizers, or
                     the TSan gate pieces (scripts/tsan_gate.sh,
                     scripts/tsan.supp, the clockwait shim thread-mode
                     builds depend on) have rotted
"""

from __future__ import annotations

import os

from .common import Finding

CMAKELISTS = "native/CMakeLists.txt"
SCRIPT = "scripts/native_sanitize.sh"
TSAN_GATE = "scripts/tsan_gate.sh"
TSAN_SUPP = "scripts/tsan.supp"
TSAN_SHIM = "native/sanitize/tsan_clockwait_shim.cpp"
MODES = ("address", "undefined", "thread")


def check(root: str) -> list:
    findings: list[Finding] = []

    def bad(path, message, line=1):
        findings.append(Finding(path, line, "sanitizer-wiring", message))

    cmake_path = os.path.join(root, CMAKELISTS)
    try:
        with open(cmake_path, encoding="utf-8") as f:
            cmake = f.read()
    except OSError:
        bad(CMAKELISTS, "native/CMakeLists.txt missing")
        cmake = ""
    if cmake:
        if "GRAFT_SANITIZE" not in cmake:
            bad(CMAKELISTS, "no GRAFT_SANITIZE preset: "
                "-DGRAFT_SANITIZE=address|undefined|thread must map onto "
                "the sanitizer build flags")
        for mode in MODES:
            if mode not in cmake:
                bad(CMAKELISTS,
                    f"sanitizer mode '{mode}' not mentioned in the "
                    "GRAFT_SANITIZE preset")
        if "-fsanitize=" not in cmake:
            bad(CMAKELISTS, "no -fsanitize compile/link options wired")

    script_path = os.path.join(root, SCRIPT)
    if not os.path.isfile(script_path):
        bad(SCRIPT, "scripts/native_sanitize.sh missing: the tier-2 "
            "ASan/UBSan gate has no driver")
        return findings
    if not os.access(script_path, os.X_OK):
        bad(SCRIPT, "scripts/native_sanitize.sh is not executable")
    with open(script_path, encoding="utf-8") as f:
        script = f.read()
    if "-fsanitize=" not in script and "GRAFT_SANITIZE" not in script:
        bad(SCRIPT, "native_sanitize.sh drives neither -fsanitize flags "
            "nor the GRAFT_SANITIZE cmake preset")
    for mode in ("address", "undefined"):
        if mode not in script:
            bad(SCRIPT, f"native_sanitize.sh does not support the "
                f"'{mode}' sanitizer")

    # The tier-2 TSan gate: driver + suppression file + the clockwait
    # shim without which this toolchain's TSan drowns in cv false
    # positives (617 on the pre-shim baseline).
    gate_path = os.path.join(root, TSAN_GATE)
    if not os.path.isfile(gate_path):
        bad(TSAN_GATE, "scripts/tsan_gate.sh missing: the tier-2 TSan "
            "gate has no driver")
    else:
        if not os.access(gate_path, os.X_OK):
            bad(TSAN_GATE, "scripts/tsan_gate.sh is not executable")
        with open(gate_path, encoding="utf-8") as f:
            gate = f.read()
        if "tsan.supp" not in gate or "TSAN_OPTIONS" not in gate:
            bad(TSAN_GATE, "tsan_gate.sh does not wire the suppression "
                "file through TSAN_OPTIONS")
    if not os.path.isfile(os.path.join(root, TSAN_SUPP)):
        bad(TSAN_SUPP, "scripts/tsan.supp missing: the TSan gate's "
            "suppression policy file is part of the wiring")
    if not os.path.isfile(os.path.join(root, TSAN_SHIM)):
        bad(TSAN_SHIM, "tsan_clockwait_shim.cpp missing: without it, "
            "thread-mode builds on this toolchain report a false "
            "double-lock + data races for every steady-clock cv wait")
    elif "shim" not in script and "tsan_clockwait" not in script:
        bad(SCRIPT, "native_sanitize.sh does not link the clockwait "
            "shim into thread-mode builds")
    return findings

"""graftlint obsspan checker: grafttrace instrumentation discipline.

The obs span API (``hotstuff_tpu/obs/spans.py``) has two invariants the
type system cannot hold for us, so this checker holds them mechanically
over the instrumented modules:

Rules:
  unclosed-span       a ``.begin_span(`` call in a function scope with
                      no ``.end_span(`` inside a ``finally`` block of
                      that scope.  An exception (or early return)
                      between begin and a bare end leaks the span and
                      skews every downstream percentile — pair them in
                      ``try/finally``, or use the ``with tracer.span()``
                      form, which needs no pairing at all.  (A scope
                      named ``__enter__`` is exempt: the context-manager
                      protocol IS the pairing — its ``__exit__`` closes
                      the span.)
  span-inline-clock   a direct ``time.time()`` / ``time.monotonic()``
                      (or bare imported ``time()``/``monotonic()``)
                      CALL inside an ``obs/`` module.  Observability
                      code must read time through the injected clock
                      only — the virtual-clock tests and the trace
                      merger's cross-host offset math both assume one
                      substitutable time source per process.  A clock
                      *reference* (``clock=time.time`` as a default
                      parameter) is legal; calling it inline is not.

Scope model is lexical per function, the timing checker's convention
(nested functions and lambdas are their own scopes).
"""

from __future__ import annotations

import ast
import glob as _glob
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source
from .timing import _scopes

# Modules that open/close obs spans, relative to the repo root (globs
# allowed).  The obs package itself plus the sidecar engine, the one
# production emitter; the span-inline-clock rule applies to the obs/
# paths only (the engine legitimately uses monotonic() for OP_STATS).
DEFAULT_TARGETS = (
    "hotstuff_tpu/obs/*.py",
    "hotstuff_tpu/sidecar/service.py",
)

_CLOCK_NAMES = {"time", "monotonic", "perf_counter", "perf_counter_ns",
                "monotonic_ns"}


def _is_inline_clock_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _CLOCK_NAMES \
            and isinstance(func.value, ast.Name) \
            and func.value.id in ("time", "_time"):
        return True
    return isinstance(func, ast.Name) and func.id in _CLOCK_NAMES


def _finally_nodes(scope_nodes):
    """All nodes lexically inside a ``finally`` block of the scope."""
    out = set()
    for node in scope_nodes:
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                out.add(stmt)
                for child in ast.walk(stmt):
                    out.add(child)
    return out


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    in_obs = "obs/" in path.replace(os.sep, "/")
    for scope, nodes in _scopes(tree):
        scope_name = getattr(scope, "name", "")
        begins = []
        ends = []
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "begin_span":
                    begins.append(node)
                elif func.attr == "end_span":
                    ends.append(node)
            if in_obs and _is_inline_clock_call(node):
                findings.append(Finding(
                    path, node.lineno, "span-inline-clock",
                    "inline clock call in an obs module: timestamps "
                    "must come from the injected clock (store the "
                    "callable at construction; time.time as a DEFAULT "
                    "is fine, calling it here is not)"))
        if not begins or scope_name == "__enter__":
            continue
        fin = _finally_nodes(nodes)
        if not any(e in fin for e in ends):
            for b in begins:
                findings.append(Finding(
                    path, b.lineno, "unclosed-span",
                    "begin_span without an end_span in a finally block "
                    "of the same scope: an exception or early return "
                    "leaks the span and skews the trace percentiles — "
                    "pair them in try/finally or use the "
                    "`with tracer.span(...)` form"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        for path in sorted(_glob.glob(os.path.join(root, target))):
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

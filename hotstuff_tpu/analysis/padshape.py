"""graftlint padded-bucket checker: every device launch size must be a
shape the sidecar warmup compiles.

The engine pre-compiles a CLOSED set of batch shapes before it binds its
socket (sidecar/service._warmup / _warmup_bulk): power-of-two buckets
from the _MIN_BUCKET floor up to MAX_SUBBATCH, then chunked-scan shapes
of 2..16 sub-batches.  Any launch whose size is NOT in that set triggers
a first-time XLA compile on the engine thread mid-traffic — the silent
30-60 s stall the warmup exists to prevent, and invisible to unit tests
(CPU compiles are fast enough to pass).  The RLC/MSM path added its own
launch shapes, which makes the discipline load-bearing in three modules
instead of one — so it graduates from a code-review convention to a
mechanical rule.

Rules:
  padded-bucket   (a) a function that fires a device launch (a
                  ``*_donated`` production entry or a ``_cached_*``
                  mesh verifier) without computing its size through a
                  bucket helper (``next_pow2`` / ``_bucket``);
                  (b) warmup/bucket constant drift: the service warmup
                  floor must equal crypto/eddsa._MIN_BUCKET, and
                  MAX_COALESCED must be a power-of-two multiple of
                  MAX_SUBBATCH (the exact chunk counts _warmup_bulk
                  compiles).
"""

from __future__ import annotations

import ast
import os
import re

from .common import (Finding, _eval_int, apply_suppressions,
                     module_int_constants)
from .hotpath import _attr_chain

# The modules whose functions launch padded device programs.
DEFAULT_TARGETS = (
    "hotstuff_tpu/crypto/eddsa.py",
    "hotstuff_tpu/parallel/sharded_verify.py",
)

EDDSA = "hotstuff_tpu/crypto/eddsa.py"
SERVICE = "hotstuff_tpu/sidecar/service.py"

# Helpers that implement THE bucketing rule (crypto/eddsa.next_pow2 and
# its module-private wrapper).  A launch-bearing function must route its
# size through one of these.
_BUCKET_HELPERS = {"next_pow2", "_bucket"}

# A launch: calling a donated production entry point or a cached mesh
# verifier.  ``_jit_donated`` itself is the factory, not a launch.
_LAUNCH_RE = re.compile(r"(^_cached_\w+$)|(^(?!_jit_donated$)\w+_donated$)")


def _terminal_name(call: ast.Call) -> str | None:
    chain = _attr_chain(call.func)
    if chain:
        return chain[-1]
    # _cached_verifier(mesh, n)(*arrays): the launch is the OUTER call;
    # its func is the inner Call — resolve that inner call's name.
    if isinstance(call.func, ast.Call):
        return _terminal_name(call.func)
    return None


def _check_launch_bucketing(path: str, source: str) -> list:
    findings = []
    tree = ast.parse(source, filename=path)
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        launches, bucketed = [], False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node)
            if name is None:
                continue
            if name in _BUCKET_HELPERS:
                bucketed = True
            elif _LAUNCH_RE.match(name):
                launches.append((node, name))
        if launches and not bucketed:
            for node, name in launches:
                findings.append(Finding(
                    path, node.lineno, "padded-bucket",
                    f"{fn.name}() launches {name} without routing the "
                    "batch size through next_pow2/_bucket: a non-bucket "
                    "shape compiles on the engine thread mid-traffic "
                    "(warmup only covers power-of-two buckets)"))
    return findings


def _line_of(source: str, pattern: str) -> int:
    m = re.search(pattern, source, re.MULTILINE)
    return source[:m.start()].count("\n") + 1 if m else 1


def _warmup_floor(service_src: str) -> int | None:
    """The literal start size _warmup hands _warm_shapes."""
    tree = ast.parse(service_src)
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "_warmup":
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == "_warm_shapes" and \
                        len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Constant) and \
                        isinstance(node.args[1].value, int):
                    return node.args[1].value
    return None


def _check_warmup_constants(root: str) -> list:
    findings = []

    def _read(rel):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    eddsa_src = _read(EDDSA)
    service_src = _read(SERVICE)
    if eddsa_src is None or service_src is None:
        for rel, src in ((EDDSA, eddsa_src), (SERVICE, service_src)):
            if src is None:
                findings.append(Finding(
                    rel, 1, "padded-bucket",
                    "source file not found — the warmup cross-check "
                    "cannot anchor; fix the source or update padshape.py"))
        return findings

    eddsa_consts = module_int_constants(eddsa_src, EDDSA)
    min_bucket = eddsa_consts.get("_MIN_BUCKET")
    max_subbatch = eddsa_consts.get("MAX_SUBBATCH")
    floor = _warmup_floor(service_src)
    if min_bucket is None or max_subbatch is None:
        findings.append(Finding(
            EDDSA, 1, "padded-bucket",
            "_MIN_BUCKET/MAX_SUBBATCH not found — the warmup cross-check "
            "cannot anchor"))
        return findings
    if floor is None:
        findings.append(Finding(
            SERVICE, _line_of(service_src, r"^def _warmup\b"),
            "padded-bucket",
            "_warmup's _warm_shapes start literal not found — the "
            "warmup floor cross-check cannot anchor"))
    elif floor != min_bucket:
        findings.append(Finding(
            SERVICE, _line_of(service_src, r"^def _warmup\b"),
            "padded-bucket",
            f"warmup floor {floor} != crypto/eddsa._MIN_BUCKET "
            f"{min_bucket}: requests bucketed below the warmed floor "
            "hit a cold shape mid-traffic"))

    # MAX_COALESCED must be a power-of-two multiple of MAX_SUBBATCH:
    # _warmup_bulk compiles chunk counts 2, 4, ... MAX_COALESCED /
    # MAX_SUBBATCH, and the chunked dispatch pads its chunk count to a
    # power of two — any other ratio leaves a launchable shape unwarmed.
    service_consts = module_int_constants(service_src, SERVICE)
    max_coalesced = service_consts.get("MAX_COALESCED")
    if max_coalesced is None:
        # MAX_COALESCED = 16 * MAX_SUBBATCH references an import the
        # plain constant scrape cannot see; evaluate it with the eddsa
        # constants in scope.
        tree = ast.parse(service_src)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "MAX_COALESCED":
                try:
                    max_coalesced = _eval_int(node.value, dict(eddsa_consts))
                except ValueError:
                    pass
    if max_coalesced is None:
        findings.append(Finding(
            SERVICE, 1, "padded-bucket",
            "MAX_COALESCED not found — the bulk-warmup cross-check "
            "cannot anchor"))
    else:
        ratio, ok = divmod(max_coalesced, max_subbatch)
        if ok != 0 or ratio < 1 or (ratio & (ratio - 1)) != 0:
            findings.append(Finding(
                SERVICE, _line_of(service_src, r"^MAX_COALESCED\s*="),
                "padded-bucket",
                f"MAX_COALESCED={max_coalesced} is not a power-of-two "
                f"multiple of MAX_SUBBATCH={max_subbatch}: the chunked "
                "dispatch pads chunk counts to powers of two, so a "
                "coalesced backlog could launch a shape _warmup_bulk "
                "never compiled"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: python source} mapping (unit-test entry point):
    launch-bucketing only — the warmup constant cross-check needs the
    real tree (see check)."""
    findings = []
    for path, src in sources.items():
        findings += _check_launch_bucketing(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for rel in targets:
        path = os.path.join(root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    findings = check_sources(sources)
    findings += _check_warmup_constants(root)
    return findings

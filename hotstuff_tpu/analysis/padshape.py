"""graftlint padded-bucket checker: every device launch size must be a
shape the sidecar warmup compiles.

The engine pre-compiles a CLOSED set of batch shapes before it binds its
socket (sidecar/service._warmup / _warmup_bulk): power-of-two buckets
from the _MIN_BUCKET floor up to MAX_SUBBATCH, then chunked-scan shapes
of 2..16 sub-batches.  Any launch whose size is NOT in that set triggers
a first-time XLA compile on the engine thread mid-traffic — the silent
30-60 s stall the warmup exists to prevent, and invisible to unit tests
(CPU compiles are fast enough to pass).  The RLC/MSM path added its own
launch shapes, which makes the discipline load-bearing in three modules
instead of one — so it graduates from a code-review convention to a
mechanical rule.

Rules:
  padded-bucket   (a) a function that fires a device launch (a
                  ``*_donated`` production entry or a ``_cached_*``
                  mesh verifier) without computing its size through a
                  bucket helper (``next_pow2`` / ``_bucket``, or the
                  shard-aligned helpers on the mesh path);
                  (b) warmup/bucket constant drift: the service warmup
                  floor must equal crypto/eddsa._MIN_BUCKET, and
                  MAX_COALESCED must be a power-of-two multiple of
                  MAX_SUBBATCH (the exact chunk counts _warmup_bulk
                  compiles).
  shard-misaligned-launch
                  On the MESH path (parallel/sharded_verify.py and the
                  scheduler's shape registry), launch-size arithmetic
                  must route through THE shard-alignment helpers
                  (parallel/shard_shapes.shard_bucket /
                  shard_aligned_rows): a function that fires a mesh
                  launch (``_cached_*``) or hand-rolls per-device size
                  math (multiply/divide by an ``n_dev``/``n_devices``
                  operand) without calling a shard helper can produce a
                  per-shard row count warmup never compiled (3000 sigs
                  on 8 devices -> 375-row shards) — a cold XLA compile
                  on the engine thread mid-traffic.  next_pow2 alone is
                  NOT sufficient there: the power-of-two discipline must
                  be applied per shard, which only the helpers encode.
  pallas-interpret-in-prod
                  An ``interpret=True`` LITERAL on a pallas_call outside
                  the graftkern backend probe (ops/kern/backend.py's
                  interpret_default): production kernels must select
                  interpreter mode OFF THE BACKEND at trace time
                  (``interpret=interpret_default()``), or a TPU
                  deployment silently runs the Pallas interpreter —
                  orders of magnitude slower, invisible to CPU unit
                  tests (which run interpreted either way).
"""

from __future__ import annotations

import ast
import os
import re

from .common import (Finding, _eval_int, apply_suppressions,
                     module_int_constants, parse_source, read_source)
from .hotpath import _attr_chain

# The modules whose functions launch padded device programs.  The
# graftkern dir rides the scan so the pallas-interpret-in-prod rule
# sees every kernel module (directories scan non-recursively, like the
# hotpath checker's).
DEFAULT_TARGETS = (
    "hotstuff_tpu/crypto/eddsa.py",
    "hotstuff_tpu/parallel/sharded_verify.py",
    "hotstuff_tpu/sidecar/sched/shapes.py",
    "hotstuff_tpu/ops/kern",
)

# The MESH-path modules: launch sizing there must go through the
# shard-alignment helpers, not just any bucket helper.  The helper
# module itself (parallel/shard_shapes.py) is the definition site and
# deliberately NOT a target.
MESH_TARGETS = (
    "hotstuff_tpu/parallel/sharded_verify.py",
    "hotstuff_tpu/sidecar/sched/shapes.py",
)

EDDSA = "hotstuff_tpu/crypto/eddsa.py"
SERVICE = "hotstuff_tpu/sidecar/service.py"

# Helpers that implement THE bucketing rules: crypto/eddsa.next_pow2 and
# its module-private wrapper, plus the mesh shard-alignment helpers
# (parallel/shard_shapes — mesh_chunk_count is the graftscale
# whole-backlog scan's chunk arithmetic, the same single-home rule for
# the (g, rows) scan shapes).  A launch-bearing function must route its
# size through one of these.
_SHARD_HELPERS = {"shard_bucket", "shard_aligned_rows", "mesh_chunk_count"}
_BUCKET_HELPERS = {"next_pow2", "_bucket"} | _SHARD_HELPERS

# An n_devices-ish operand: arithmetic against one of these names is the
# signature of hand-rolled per-device size math.
_NDEV_RE = re.compile(r"^n_dev(ices)?$")

# Mult/div/mod against a device count is size math; Add/Sub is padding
# arithmetic on already-derived sizes and stays legal.
_SIZE_MATH_OPS = (ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)

# A launch: calling a donated production entry point or a cached mesh
# verifier.  ``_jit_donated`` itself is the factory, not a launch.
_LAUNCH_RE = re.compile(r"(^_cached_\w+$)|(^(?!_jit_donated$)\w+_donated$)")


def _terminal_name(call: ast.Call) -> str | None:
    chain = _attr_chain(call.func)
    if chain:
        return chain[-1]
    # _cached_verifier(mesh, n)(*arrays): the launch is the OUTER call;
    # its func is the inner Call — resolve that inner call's name.
    if isinstance(call.func, ast.Call):
        return _terminal_name(call.func)
    return None


def _is_launch(call: ast.Call, name: str) -> bool:
    """A device launch: a ``*_donated`` production entry called with
    arrays, or a cached mesh verifier in its two-level
    ``_cached_x(mesh)(arrays)`` form.  A DIRECT ``_cached_*`` call is the
    factory handing back the jit (the donated wrappers share the plain
    cache on CPU) — referencing it launches nothing."""
    if not _LAUNCH_RE.match(name):
        return False
    if name.startswith("_cached_"):
        return isinstance(call.func, ast.Call)
    return True


def _check_launch_bucketing(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        launches, bucketed = [], False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node)
            if name is None:
                continue
            if name in _BUCKET_HELPERS:
                bucketed = True
            elif _is_launch(node, name):
                launches.append((node, name))
        if launches and not bucketed:
            for node, name in launches:
                findings.append(Finding(
                    path, node.lineno, "padded-bucket",
                    f"{fn.name}() launches {name} without routing the "
                    "batch size through next_pow2/_bucket: a non-bucket "
                    "shape compiles on the engine thread mid-traffic "
                    "(warmup only covers power-of-two buckets)"))
    return findings


def _operand_name(node: ast.AST) -> str | None:
    """Terminal identifier of a Name/Attribute operand (self.n_devices ->
    n_devices)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _outer_functions(tree: ast.Module):
    """Module-level functions and class methods — the per-function scope
    both rules reason in (nested closures belong to their enclosing
    function: a dispatch() closure launching a mesh program is aligned by
    the pack function that built its buffers)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub


def _check_shard_alignment(path: str, source: str) -> list:
    """The shard-misaligned-launch rule over one mesh-path module: any
    function that (a) fires a ``_cached_*`` mesh launch or (b) does size
    math (mul/div/mod) against an ``n_dev``/``n_devices`` operand must
    call a shard-alignment helper."""
    findings = []
    tree = parse_source(source, path)
    for fn in _outer_functions(tree):
        shard_helper_called = False
        evidence = []  # (node, what)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _terminal_name(node)
                if name in _SHARD_HELPERS:
                    shard_helper_called = True
                elif name is not None and name.startswith("_cached_") \
                        and _is_launch(node, name):
                    evidence.append((node, f"mesh launch {name}"))
            elif isinstance(node, ast.BinOp) and \
                    isinstance(node.op, _SIZE_MATH_OPS):
                for side in (node.left, node.right):
                    opname = _operand_name(side)
                    if opname and _NDEV_RE.match(opname):
                        evidence.append(
                            (node, f"size math against {opname}"))
                        break
        if evidence and not shard_helper_called:
            for node, what in evidence:
                findings.append(Finding(
                    path, node.lineno, "shard-misaligned-launch",
                    f"{fn.name}() has {what} without routing through "
                    "shard_bucket/shard_aligned_rows: a hand-rolled "
                    "per-device size can land on a per-shard shape "
                    "warmup never compiled (a cold XLA compile on the "
                    "engine thread mid-traffic)"))
    return findings


# The one function allowed to pin interpret mode with a literal: the
# backend probe itself — qualified by BOTH module and name, so a shim
# merely NAMED interpret_default in some other kernel module cannot
# claim the exemption (ops/kern/backend.interpret_default reads the
# backend; interpret_probe carries a worked suppression).
_INTERPRET_EXEMPT = {("hotstuff_tpu/ops/kern/backend.py",
                      "interpret_default")}


def _check_pallas_interpret(path: str, source: str) -> list:
    """The pallas-interpret-in-prod rule over one module: flag
    ``interpret=True`` literals on ``pallas_call`` invocations whose
    enclosing function is not the backend probe."""
    findings = []
    tree = parse_source(source, path)
    norm = path.replace(os.sep, "/")

    def visit(node, fname):
        for child in ast.iter_child_nodes(node):
            child_fname = fname
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_fname = child.name
            if isinstance(child, ast.Call):
                name = _terminal_name(child)
                if name == "pallas_call" and \
                        (norm, fname) not in _INTERPRET_EXEMPT:
                    for kw in child.keywords:
                        if kw.arg == "interpret" and \
                                isinstance(kw.value, ast.Constant) and \
                                kw.value.value is True:
                            findings.append(Finding(
                                path, kw.value.lineno,
                                "pallas-interpret-in-prod",
                                f"{fname or '<module>'}() pins "
                                "interpret=True on a pallas_call: a TPU "
                                "deployment would silently run the "
                                "Pallas interpreter; select off the "
                                "backend via ops/kern/backend."
                                "interpret_default() (or suppress with "
                                "a rationale for a forced-interpreter "
                                "probe)"))
            visit(child, child_fname)

    visit(tree, None)
    return findings


def _line_of(source: str, pattern: str) -> int:
    m = re.search(pattern, source, re.MULTILINE)
    return source[:m.start()].count("\n") + 1 if m else 1


def _warmup_floor(service_src: str) -> int | None:
    """The literal start size _warmup hands _warm_shapes."""
    tree = parse_source(service_src)
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "_warmup":
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == "_warm_shapes" and \
                        len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Constant) and \
                        isinstance(node.args[1].value, int):
                    return node.args[1].value
    return None


def _check_warmup_constants(root: str) -> list:
    findings = []

    def _read(rel):
        try:
            return read_source(os.path.join(root, rel))
        except OSError:
            return None

    eddsa_src = _read(EDDSA)
    service_src = _read(SERVICE)
    if eddsa_src is None or service_src is None:
        for rel, src in ((EDDSA, eddsa_src), (SERVICE, service_src)):
            if src is None:
                findings.append(Finding(
                    rel, 1, "padded-bucket",
                    "source file not found — the warmup cross-check "
                    "cannot anchor; fix the source or update padshape.py"))
        return findings

    eddsa_consts = module_int_constants(eddsa_src, EDDSA)
    min_bucket = eddsa_consts.get("_MIN_BUCKET")
    max_subbatch = eddsa_consts.get("MAX_SUBBATCH")
    floor = _warmup_floor(service_src)
    if min_bucket is None or max_subbatch is None:
        findings.append(Finding(
            EDDSA, 1, "padded-bucket",
            "_MIN_BUCKET/MAX_SUBBATCH not found — the warmup cross-check "
            "cannot anchor"))
        return findings
    if floor is None:
        findings.append(Finding(
            SERVICE, _line_of(service_src, r"^def _warmup\b"),
            "padded-bucket",
            "_warmup's _warm_shapes start literal not found — the "
            "warmup floor cross-check cannot anchor"))
    elif floor != min_bucket:
        findings.append(Finding(
            SERVICE, _line_of(service_src, r"^def _warmup\b"),
            "padded-bucket",
            f"warmup floor {floor} != crypto/eddsa._MIN_BUCKET "
            f"{min_bucket}: requests bucketed below the warmed floor "
            "hit a cold shape mid-traffic"))

    # MAX_COALESCED must be a power-of-two multiple of MAX_SUBBATCH:
    # _warmup_bulk compiles chunk counts 2, 4, ... MAX_COALESCED /
    # MAX_SUBBATCH, and the chunked dispatch pads its chunk count to a
    # power of two — any other ratio leaves a launchable shape unwarmed.
    service_consts = module_int_constants(service_src, SERVICE)
    max_coalesced = service_consts.get("MAX_COALESCED")
    if max_coalesced is None:
        # MAX_COALESCED = 16 * MAX_SUBBATCH references an import the
        # plain constant scrape cannot see; evaluate it with the eddsa
        # constants in scope.
        tree = parse_source(service_src)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "MAX_COALESCED":
                try:
                    max_coalesced = _eval_int(node.value, dict(eddsa_consts))
                except ValueError:
                    pass
    if max_coalesced is None:
        findings.append(Finding(
            SERVICE, 1, "padded-bucket",
            "MAX_COALESCED not found — the bulk-warmup cross-check "
            "cannot anchor"))
    else:
        ratio, ok = divmod(max_coalesced, max_subbatch)
        if ok != 0 or ratio < 1 or (ratio & (ratio - 1)) != 0:
            findings.append(Finding(
                SERVICE, _line_of(service_src, r"^MAX_COALESCED\s*="),
                "padded-bucket",
                f"MAX_COALESCED={max_coalesced} is not a power-of-two "
                f"multiple of MAX_SUBBATCH={max_subbatch}: the chunked "
                "dispatch pads chunk counts to powers of two, so a "
                "coalesced backlog could launch a shape _warmup_bulk "
                "never compiled"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: python source} mapping (unit-test entry point):
    launch-bucketing + pallas-interpret literals + (for mesh-path
    modules) shard alignment — the warmup constant cross-check needs
    the real tree (see check)."""
    findings = []
    for path, src in sources.items():
        findings += _check_launch_bucketing(path, src)
        findings += _check_pallas_interpret(path, src)
        if path in MESH_TARGETS:
            findings += _check_shard_alignment(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for rel in targets:
        path = os.path.join(root, rel)
        if os.path.isdir(path):
            for f in sorted(os.listdir(path)):
                if f.endswith(".py"):
                    try:
                        sources[f"{rel}/{f}"] = read_source(
                            os.path.join(path, f))
                    except OSError:
                        continue
            continue
        try:
            sources[rel] = read_source(path)
        except OSError:
            continue
    findings = check_sources(sources)
    findings += _check_warmup_constants(root)
    return findings

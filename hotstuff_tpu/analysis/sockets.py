"""graftlint socket checker: every socket operation on the harness and
sidecar boundary must be explicitly bounded.

The graftchaos postmortem class this rule exists for: a dead sidecar
used to cost every verify a fresh connect wait, and a wedged peer could
park a harness thread on a bare ``recv`` forever — failures that only
show up mid-run, when the fault plan (or real life) kills a process.
The repo convention is that *every* ``connect``/``recv``/``accept`` in
the control plane carries an explicit bound: a ``timeout=`` argument on
``socket.create_connection``, or a ``settimeout(...)`` configured on the
same socket in the same lexical scope.

Rule:
  unbounded-socket-op   a socket ``connect``/``accept``/``recv``/
                        ``recv_into`` call (or ``create_connection``
                        without a timeout argument) with no visible
                        bound in its scope; also a ``subprocess.run``
                        whose argv is ssh/scp with no ``timeout=``
                        keyword — ssh's ConnectTimeout bounds the
                        *dial*, not a hung remote command, so an
                        unbounded ssh subprocess is the same parked
                        thread a bare ``recv`` is (graftwan widened
                        the rule to ``harness/remote.py`` for exactly
                        this: a wedged fleet host must surface as an
                        error, never hang the orchestrator)

Receiver detection is deliberately name-based (identifiers containing
``sock``/``socket``/``conn``; argv expressions mentioning ``ssh``/
``scp``), not dataflow: the boundary modules use conventional socket
names, bare parameters carry no assignment history, and a rename that
dodges the rule is exactly the kind of edit a reviewer should see.  The
one deliberately unbounded op in the tree — the server-side frame read
idling between requests in ``sidecar/protocol._read_exact`` — carries
the inline suppression with its rationale, per the suppression policy
in analysis/README.md.
"""

from __future__ import annotations

import ast
import os
import re

from .common import Finding, apply_suppressions, parse_source, \
    read_source

# Modules on the process/socket boundary: the sidecar (service, client,
# protocol), the harness (local/remote orchestration), and the graftchaos
# fault layer that reaches into both.
DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar",
    "hotstuff_tpu/harness",
    "hotstuff_tpu/chaos",
)

_SOCKET_NAME_RE = re.compile(r"sock|socket|conn", re.IGNORECASE)
_SOCKET_OPS = {"connect", "accept", "recv", "recv_into", "recvfrom"}
_SSH_ARGV_RE = re.compile(r"\bssh\b|\bscp\b|_ssh_", re.IGNORECASE)


def _last_ident(node: ast.AST):
    """Rightmost identifier of a receiver expression (``self._sock`` ->
    ``_sock``; ``sock`` -> ``sock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _scopes(tree: ast.Module):
    """(scope, direct nodes) pairs with nested function/lambda bodies cut
    out — a timeout configured in one function does not bound another."""
    nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def direct_nodes(root):
        out = []
        stack = [iter(ast.iter_child_nodes(root))]
        while stack:
            try:
                node = next(stack[-1])
            except StopIteration:
                stack.pop()
                continue
            if isinstance(node, nested):
                continue
            out.append(node)
            stack.append(iter(ast.iter_child_nodes(node)))
        return out

    yield tree, direct_nodes(tree)
    for node in ast.walk(tree):
        if isinstance(node, nested):
            yield node, direct_nodes(node)


def _has_timeout_arg(call: ast.Call) -> bool:
    """True when a create_connection call carries a non-None timeout
    (2nd positional, or the ``timeout=`` keyword — a plain ``timeout=x``
    variable counts: the bound is the caller's explicit choice)."""
    if len(call.args) >= 2:
        a = call.args[1]
        return not (isinstance(a, ast.Constant) and a.value is None)
    return _has_timeout_kwarg(call)


def _has_timeout_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


def _mentions_ssh(node: ast.AST) -> bool:
    """True when an argv expression visibly involves ssh/scp: a string
    literal naming the binary, or an identifier like ``_ssh_base``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and _SSH_ARGV_RE.search(sub.value):
            return True
        if isinstance(sub, ast.Name) and _SSH_ARGV_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and _SSH_ARGV_RE.search(sub.attr):
            return True
    return False


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    for _scope, nodes in _scopes(tree):
        bounded = set()   # receiver idents with a settimeout in scope
        suspects = []     # (node, op, receiver ident)
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr == "settimeout":
                ident = _last_ident(func.value)
                if ident:
                    bounded.add(ident)
            elif func.attr == "run" and isinstance(func.value, ast.Name) \
                    and func.value.id == "subprocess":
                if node.args and _mentions_ssh(node.args[0]) \
                        and not _has_timeout_kwarg(node):
                    findings.append(Finding(
                        path, node.lineno, "unbounded-socket-op",
                        "subprocess.run of an ssh/scp argv without a "
                        "timeout= keyword: ssh's ConnectTimeout bounds "
                        "the dial, not a hung remote command — a wedged "
                        "fleet host parks this thread forever; pass an "
                        "explicit subprocess timeout"))
            elif func.attr == "create_connection":
                if not _has_timeout_arg(node):
                    findings.append(Finding(
                        path, node.lineno, "unbounded-socket-op",
                        "socket.create_connection without a timeout "
                        "argument: a dead peer parks this thread for the "
                        "kernel's connect timeout (minutes); pass "
                        "timeout= explicitly"))
            elif func.attr in _SOCKET_OPS:
                ident = _last_ident(func.value)
                if ident and _SOCKET_NAME_RE.search(ident):
                    suspects.append((node, func.attr, ident))
        for node, op, ident in suspects:
            if ident in bounded:
                continue
            findings.append(Finding(
                path, node.lineno, "unbounded-socket-op",
                f"socket .{op}() on {ident!r} with no settimeout() in "
                "this scope: a wedged or chaos-killed peer blocks this "
                "thread indefinitely; bound the socket (settimeout / "
                "create_connection timeout) or carry a justified "
                "suppression"))
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        base = os.path.join(root, target)
        if os.path.isfile(base):
            paths = [base]
        elif os.path.isdir(base):
            paths = []
            for dirpath, _dirnames, filenames in os.walk(base):
                paths += [os.path.join(dirpath, f)
                          for f in sorted(filenames)]
        else:
            continue
        for path in paths:
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

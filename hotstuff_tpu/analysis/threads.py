"""graftsync threads checker: cross-thread sharing discipline in the
threaded Python modules.

PRs 3-7 made the Python side genuinely concurrent — the sidecar engine
and pack worker, connection reader/writer threads, the obs
``MetricsSampler``, and the chaos ``PlanRunner`` all share instance
state across threads — but nothing held the sharing discipline
mechanically.  This checker does, per class, from the thread entries the
class itself creates:

Thread model.  A method is a THREAD ENTRY when the class starts it on
its own thread: ``threading.Thread(target=self.m, ...)`` or a pool
submit ``self._pool.submit(self.m, ...)``.  The thread body is the
call-graph closure of the entry over ``self.<method>`` references
(method references passed as callbacks count — a lexical tool cannot
see which thread later calls them, so it assumes the spawning entry
does; over-approximation here is deliberate, the suppression comment is
where a human records the sharper fact).

Rules:
  unlocked-shared-write
      An instance attribute written from a thread body AND from outside
      it (or from two distinct entries' bodies) where the write sites do
      not all sit under ``with self.<lock>:`` of one shared
      ``threading.Lock``/``RLock``/``Condition`` attribute.  Writes are
      assignments (``self.x = ...``, ``self.x[...] = ...``, augmented)
      and the mutating container calls (append/add/pop/update/...).
      ``__init__`` writes are exempt — construction happens-before
      ``Thread.start()``.  Evidence-comment suppressions carry the cases
      the lexical model over-approximates (e.g. a closure built on one
      thread but executed on another).
  daemon-thread-without-stop-flag
      A ``threading.Thread(..., daemon=True, target=self.m)`` whose
      body never consults a stop flag: a ``threading.Event`` attribute
      (or an attribute derived from one in ``__init__``, like the
      sampler's ``self._wait = ... or self._stop.wait``).  Daemonized
      loops with no stop signal die only with the interpreter — a
      teardown that cannot stop its threads leaks them into the next
      test and tears files out from under them.
  thread-loop-inline-clock
      An inline clock/sleep call (``time.time()``, ``monotonic()``,
      ``time.sleep()``, ...) inside a thread body of a class that takes
      an INJECTABLE clock (``clock``/``wall``/``wait``/``sleep``
      parameters on ``__init__``, the obs convention): the virtual-clock
      tests drive those loops manually, and one inline read splits the
      time base mid-loop.  Classes without injected clocks are out of
      scope — the engine's ``monotonic()`` telemetry reads are the
      documented legitimate use (see analysis/README.md).

Lock detection is name-assisted like the sockets rule: an attribute
assigned ``threading.Lock()``/``RLock()``/``Condition()`` in
``__init__`` is a lock; so is one assigned from an ``__init__``
parameter whose name mentions lock/cond (the scheduler hands its
Condition to each ClassQueue that way).
"""

from __future__ import annotations

import ast
import os

from .common import Finding, apply_suppressions, parse_source, read_source

# The threaded modules: every file that calls threading.Thread(target=
# self.*) or runs a pool worker today.  lint_gate --must-cover pins each
# one so a module cannot silently leave the scan.
DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar/service.py",
    "hotstuff_tpu/sidecar/guard.py",
    "hotstuff_tpu/sidecar/ring.py",
    "hotstuff_tpu/sidecar/sched",
    "hotstuff_tpu/obs/sampler.py",
    "hotstuff_tpu/chaos/runner.py",
    "hotstuff_tpu/harness/faults.py",
    "hotstuff_tpu/harness/local.py",
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_EVENT_CTOR = "Event"
_LOCKISH_PARAM = ("lock", "cond")
_CLOCK_PARAMS = {"clock", "wall", "wait", "sleep", "now"}
_CLOCK_CALLS = {"time", "monotonic", "sleep", "perf_counter",
                "perf_counter_ns", "monotonic_ns"}
# Container mutations that count as writes (shared-state hazards the
# assignment scan alone would miss).  Deliberately excludes ``set`` —
# Event.set()/Oneshot.set() are synchronization, not shared mutation.
_MUTATORS = {"append", "appendleft", "add", "extend", "insert", "update",
             "setdefault", "pop", "popleft", "popitem", "remove",
             "discard", "clear"}


def _self_attr(node):
    """'x' for a ``self.x`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread"


class _Write:
    __slots__ = ("attr", "line", "method", "locks")

    def __init__(self, attr, line, method, locks):
        self.attr = attr
        self.line = line
        self.method = method
        self.locks = frozenset(locks)


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body: writes (with the self-lock attrs
    held at each site), self.<name> references, thread spawns, and
    inline clock calls."""

    def __init__(self, lock_attrs):
        self._lock_attrs = lock_attrs
        self._held: list[str] = []
        self.writes: list[tuple] = []      # (attr, line, held-locks)
        self.refs: set[str] = set()        # every self.<name> referenced
        self.spawns: list[tuple] = []      # (target-method|None, daemon, line)
        self.clock_calls: list[tuple] = []  # (line, rendered-name)

    def visit_With(self, node: ast.With):
        held = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self._lock_attrs:
                held.append(attr)
        self._held += held
        self.generic_visit(node)
        if held:
            del self._held[-len(held):]

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None:
            self.refs.add(attr)
        self.generic_visit(node)

    def _note_write(self, target):
        # self.x = / self.x[...] = / self.x.y = … — the attribute whose
        # object is mutated is the shared state.  Tuple targets unpack.
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._note_write(elt)
            return
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            attr = _self_attr(node)
            if attr is not None:
                self.writes.append((attr, target.lineno, tuple(self._held)))
                return
            node = node.value

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._note_write(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._note_write(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._note_write(node.target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        # self.x.append(...) and friends are writes to self.x
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr is not None:
                self.writes.append((attr, node.lineno, tuple(self._held)))
        # thread spawns
        if _is_thread_ctor(node):
            target = None
            daemon = False
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _self_attr(kw.value)
                elif kw.arg == "daemon":
                    daemon = isinstance(kw.value, ast.Constant) and \
                        bool(kw.value.value)
            self.spawns.append((target, daemon, node.lineno))
        elif isinstance(f, ast.Attribute) and f.attr == "submit" \
                and node.args:
            target = _self_attr(node.args[0])
            if target is not None:
                self.spawns.append((target, False, node.lineno))
        # inline clocks: time.time()/monotonic()/sleep() called directly
        name = None
        if isinstance(f, ast.Attribute) and f.attr in _CLOCK_CALLS and \
                isinstance(f.value, ast.Name) and \
                f.value.id in ("time", "_time"):
            name = f"time.{f.attr}"
        elif isinstance(f, ast.Name) and f.id in _CLOCK_CALLS:
            name = f.id
        if name is not None:
            self.clock_calls.append((node.lineno, name))
        self.generic_visit(node)


def _init_attr_facts(init: ast.FunctionDef | None):
    """(lock_attrs, stopish_attrs, clock_injected) from ``__init__``."""
    lock_attrs: set[str] = set()
    stopish: set[str] = set()
    clock_injected = False
    if init is None:
        return lock_attrs, stopish, clock_injected
    args = init.args
    params = [a.arg for a in args.args + args.kwonlyargs]
    clock_injected = bool(_CLOCK_PARAMS & set(params))
    lockish_params = {p for p in params
                      if any(s in p.lower() for s in _LOCKISH_PARAM)}
    for node in ast.walk(init):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        attr = _self_attr(node.targets[0])
        if attr is None:
            continue
        v = node.value
        if isinstance(v, ast.Call):
            f = v.func
            ctor = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if ctor in _LOCK_CTORS:
                lock_attrs.add(attr)
            elif ctor == _EVENT_CTOR:
                stopish.add(attr)
        if isinstance(v, ast.Name) and v.id in lockish_params:
            lock_attrs.add(attr)
        # an attr derived from a stop event (``self._wait = wait or
        # self._stop.wait``) is itself a stop signal
        for sub in ast.walk(v):
            if _self_attr(sub) in stopish:
                stopish.add(attr)
                break
    return lock_attrs, stopish, clock_injected


def _check_class(path: str, cls: ast.ClassDef) -> list:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    lock_attrs, stopish, clock_injected = _init_attr_facts(
        methods.get("__init__"))

    scans = {name: _MethodScan(lock_attrs) for name in methods}
    for name, node in methods.items():
        scans[name].visit(node)

    entries = {}  # entry method name -> (daemon, spawn line)
    for scan in scans.values():
        for target, daemon, line in scan.spawns:
            if target in methods:
                prev = entries.get(target)
                entries[target] = (daemon or (prev and prev[0]) or False,
                                   line if prev is None else prev[1])
    if not entries:
        return []

    # call-graph closure per entry over self.<method> references
    reach = {}
    for entry in entries:
        seen = {entry}
        frontier = [entry]
        while frontier:
            m = frontier.pop()
            for ref in scans[m].refs:
                if ref in methods and ref not in seen:
                    seen.add(ref)
                    frontier.append(ref)
        reach[entry] = seen

    findings = []

    # -- unlocked-shared-write ---------------------------------------------
    by_attr: dict[str, list[_Write]] = {}
    for name, scan in scans.items():
        if name == "__init__":
            continue  # construction happens-before Thread.start()
        for attr, line, held in scan.writes:
            by_attr.setdefault(attr, []).append(
                _Write(attr, line, name, held))
    thread_methods = {m for e in entries for m in reach[e]}
    for attr, writes in sorted(by_attr.items()):
        entry_sets = set()
        outside = False
        for w in writes:
            reached_by = frozenset(e for e in entries
                                   if w.method in reach[e])
            if reached_by:
                entry_sets.add(reached_by)
            else:
                outside = True
        inside = bool(entry_sets)
        multi_entry = len({e for s in entry_sets for e in s}) > 1
        if not (inside and (outside or multi_entry)):
            continue
        common = frozenset.intersection(
            *(w.locks for w in writes)) if writes else frozenset()
        if common:
            continue  # every write site holds the same lock
        for w in writes:
            where = f"in the thread body of {w.method}()" \
                if w.method in thread_methods else "outside any thread body"
            findings.append(Finding(
                path, w.line, "unlocked-shared-write",
                f"self.{attr} is written cross-thread (site {where}) "
                f"without one shared lock over every write site: wrap "
                f"each write in `with self.<lock>:` of the same "
                f"threading.Lock/RLock attribute, or carry an "
                f"evidence-comment suppression saying why this site "
                f"cannot race (class {cls.name})"))

    # -- daemon-thread-without-stop-flag -----------------------------------
    for entry, (daemon, line) in sorted(entries.items()):
        if not daemon:
            continue
        consulted = any(s in scans[m].refs
                        for m in reach[entry] for s in stopish)
        if not consulted:
            findings.append(Finding(
                path, line, "daemon-thread-without-stop-flag",
                f"daemon thread target {cls.name}.{entry}() never "
                f"consults a stop flag: give the class a threading.Event "
                f"the loop checks (is_set/wait) so teardown can stop the "
                f"thread instead of leaking it into the next run"))

    # -- thread-loop-inline-clock ------------------------------------------
    if clock_injected:
        for m in sorted(thread_methods):
            for line, name in scans[m].clock_calls:
                findings.append(Finding(
                    path, line, "thread-loop-inline-clock",
                    f"inline {name}() in the thread body {cls.name}."
                    f"{m}() of a clock-injected class: read time through "
                    f"the injected clock/wall/wait/sleep callables only "
                    f"— one inline read splits the time base the "
                    f"virtual-clock tests drive"))

    return findings


def check_source(path: str, source: str) -> list:
    findings = []
    tree = parse_source(source, path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings += _check_class(path, node)
    return findings


def check_sources(sources: dict) -> list:
    """Lint a {path: source} mapping (the unit-test entry point)."""
    findings = []
    for path, src in sources.items():
        findings += check_source(path, src)
    return sorted(apply_suppressions(findings, sources),
                  key=lambda f: (f.path, f.line))


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    sources = {}
    for target in targets:
        base = os.path.join(root, target)
        if os.path.isfile(base):
            paths = [base]
        elif os.path.isdir(base):
            paths = []
            for dirpath, _dirnames, filenames in os.walk(base):
                paths += [os.path.join(dirpath, f)
                          for f in sorted(filenames)]
        else:
            continue
        for path in paths:
            if not path.endswith(".py"):
                continue
            sources[os.path.relpath(path, root)] = read_source(path)
    return check_sources(sources)

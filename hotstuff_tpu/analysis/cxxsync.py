"""graftsync cxxsync checker: lock-discipline annotations and atomic
memory-order hygiene over the native tree (lexer/brace-scope based —
clang-free by design, like the wire checker).

The C++ side shares state across the reactor thread, the store worker,
the sidecar reader/probe threads and the consensus actors.  Rust would
hold the discipline in the type system; here it is held by ANNOTATIONS
the checker enforces mechanically:

  ``// GUARDED_BY(<mutex>)`` on a member declaration
      Every access to that member in the declaring file and its sibling
      .cpp/.hpp must sit lexically inside a ``std::lock_guard`` /
      ``unique_lock`` / ``scoped_lock`` scope whose mutex expression's
      last component names ``<mutex>`` — except in functions whose name
      ends in ``_locked``/``_locked_`` (the repo convention for
      "caller holds the lock", shared with sched/scheduler.py's
      ``_assemble_locked``).  ``unique_lock`` regions are interrupted
      by ``lk.unlock()`` and resumed by ``lk.lock()``.
  ``// OWNED_BY(<role>)`` / ``// SHARED_OK(<why>)``
      Documentation annotations for single-thread-confined members
      (loop thread, store worker) and members that are safe to share
      without this file's mutex (atomics, internally-synchronized
      channels, immutable-after-construction handles).  The checker
      parses but does not enforce them — they exist so every member of
      an annotated struct carries an explicit sharing story.

Rules:
  guarded-member-unlocked   access to a GUARDED_BY member outside a
                            lock scope naming its mutex (and outside
                            ``*_locked`` functions).  Lambdas inherit
                            the lexical lock scopes they are written in
                            — correct for cv predicates; a DEFERRED
                            callback that touches guarded state is the
                            dynamic-race class the TSan gate owns.
  unannotated-mutex         a ``std::mutex`` member in a scanned file
                            with no GUARDED_BY naming it: a mutex that
                            guards nothing on paper guards nothing in
                            review either.
  atomic-missing-order      ``.load()/.store()/fetch_*/exchange/
                            compare_exchange`` without an explicit
                            ``std::memory_order`` argument anywhere in
                            ``native/src``.  Sequential consistency by
                            default is not the problem — UNSTATED
                            intent is: the PR 7 trace-flag load
                            (common/log.cpp) is the exemplar, one
                            relaxed load per instrumented site with the
                            ordering claim written at the site.

Suppression: ``// graftlint: disable=<rule>`` on the access's line or
the line above, same contract as the Python checkers; every suppression
carries its evidence comment.
"""

from __future__ import annotations

import os
import re

from .common import Finding

# File pairs for the annotation rules: the subsystems whose state is
# genuinely cross-thread.  Annotations declared in one file of a pair
# bind accesses in both.
DEFAULT_TARGETS = (
    "native/src/network/event_loop.hpp",
    "native/src/network/event_loop.cpp",
    "native/src/network/reliable_sender.hpp",
    "native/src/network/reliable_sender.cpp",
    "native/src/store/store.hpp",
    "native/src/store/store.cpp",
    "native/src/crypto/sidecar_client.hpp",
    "native/src/crypto/sidecar_client.cpp",
    "native/src/consensus/mempool_driver.hpp",
    "native/src/consensus/mempool_driver.cpp",
    "native/src/consensus/core.hpp",
    "native/src/consensus/core.cpp",
    # graftview: the optimistic timeout aggregator is core-thread-owned
    # state (OWNED_BY-documented); scanning it pins that story — a
    # mutex or atomic growing here must join the annotations.
    "native/src/consensus/aggregator.hpp",
    "native/src/consensus/aggregator.cpp",
    # graftsurge: the bounded-ingress gate is reactor-thread +
    # batch-maker-thread shared state behind one mutex.
    "native/src/mempool/ingress.hpp",
    # graftscope: the node METRICS sampler — hot-path atomic counter +
    # sampler-thread state behind one mutex.
    "native/src/common/metrics.hpp",
    "native/src/common/metrics.cpp",
    # graftingress: the admission-verify stage — reactor-thread enqueue
    # against a verify-worker drain, one mutex + telemetry atomics.
    "native/src/mempool/tx_verify.hpp",
    "native/src/mempool/tx_verify.cpp",
)

# The atomic rule scans the whole native tree (any .cpp/.hpp under here).
ATOMIC_ROOT = "native/src"

_GUARDED_RE = re.compile(r"//\s*GUARDED_BY\((\w+)\)")
_DOC_ANNOT_RE = re.compile(r"//\s*(?:OWNED_BY|SHARED_OK)\(")
_SUPPRESS_RE = re.compile(r"//\s*graftlint:\s*disable=([\w\-, ]+)")
_MEMBER_DECL_RE = re.compile(
    r"([A-Za-z_]\w*)\s*(?:\{[^{}]*\}|=[^;]*)?\s*;\s*$")
_LOCK_DECL_RE = re.compile(
    r"std\s*::\s*(lock_guard|unique_lock|scoped_lock)\s*"
    r"(?:<[^<>;]*(?:<[^<>;]*>)?[^<>;]*>)?\s+(\w+)\s*[({]([^;)}]*)[)}]")
_MUTEX_MEMBER_RE = re.compile(
    r"^\s*(?:mutable\s+)?std\s*::\s*mutex\s+(\w+)\s*;", re.MULTILINE)
_ATOMIC_OP_RE = re.compile(
    r"(?:\.|->)\s*(load|store|exchange|fetch_add|fetch_sub|fetch_and|"
    r"fetch_or|fetch_xor|compare_exchange_weak|compare_exchange_strong)"
    r"\s*\(")
_LAST_IDENT_RE = re.compile(r"([A-Za-z_]\w*)\s*$")


def cpp_suppressed_rules(source: str) -> dict:
    """Line (1-based) -> rules silenced there; a ``// graftlint:
    disable=...`` comment silences its own line and the next."""
    out: dict[int, set] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        out.setdefault(i + 1, set()).update(rules)
    return out


def _strip(source: str) -> str:
    """Blank comments and string/char literals, preserving offsets and
    newlines, so scope/token scans cannot be fooled by either."""
    out = list(source)
    i, n = 0, len(source)
    while i < n:
        c = source[i]
        two = source[i:i + 2]
        if two == "//":
            j = source.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif two == "/*":
            j = source.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            for k in range(i, j + 2):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 2
        elif c == "'" and i > 0 and (source[i - 1].isalnum() or
                                     source[i - 1] == "_"):
            # C++14 digit separator (20'000): part of the number, not a
            # char literal — treating it as one would swallow the file
            # to the next apostrophe.  (Cost: u8'x'-style prefixed char
            # literals would be misread; the tree has none.)
            i += 1
        elif c in "\"'":
            q = c
            j = i + 1
            while j < n and source[j] != q:
                j += 2 if source[j] == "\\" else 1
            for k in range(i + 1, min(j, n)):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 1
        else:
            i += 1
    return "".join(out)


class _Blocks:
    """Brace-matched block ranges of a stripped source, with the
    enclosing function name (if any) per block."""

    _FUNC_TAIL_RE = re.compile(
        r"([A-Za-z_~][\w]*)\s*\([^;{}()]*(?:\([^()]*\)[^;{}()]*)*\)\s*"
        r"(?:const|noexcept|override|final|mutable|->\s*[\w:<>,\s*&]+|\s)*$")

    def __init__(self, stripped: str):
        self.ranges = []  # (start, end, func_name|None) per block
        self._n = len(stripped)
        stack = []
        for i, c in enumerate(stripped):
            if c == "{":
                stack.append(i)
            elif c == "}" and stack:
                start = stack.pop()
                self.ranges.append(
                    (start, i, self._func_name(stripped, start)))
        for start in stack:  # unclosed (truncated fixture): run to EOF
            self.ranges.append(
                (start, len(stripped), self._func_name(stripped, start)))

    def _func_name(self, stripped: str, open_pos: int):
        """Name of the function this brace opens, None for non-function
        blocks (class/namespace/control).  A ``)...{`` shape is a
        function (or lambda — named ``<lambda>``)."""
        head = stripped[max(0, open_pos - 400):open_pos]
        m = self._FUNC_TAIL_RE.search(head)
        if m:
            name = m.group(1)
            if name in ("if", "while", "for", "switch", "catch",
                        "return", "sizeof", "new", "delete"):
                return None
            return name.split("::")[-1]
        if re.search(r"\)\s*(?:const|noexcept|mutable|\s)*$", head) or \
                re.search(r"\]\s*$", head):
            return "<lambda>"
        return None

    def enclosing_functions(self, pos: int):
        """Function names of every function block containing ``pos``
        (innermost last)."""
        out = []
        for start, end, name in sorted(self.ranges):
            if start < pos < end and name is not None:
                out.append(name)
        return out

    def block_end(self, pos: int) -> int:
        """End of the innermost block containing ``pos``."""
        best = None
        for start, end, _name in self.ranges:
            if start < pos < end and (best is None or
                                      end - start < best[1] - best[0]):
                best = (start, end)
        return best[1] if best else self._n


class _LockScope:
    __slots__ = ("mutexes", "ranges")

    def __init__(self, mutexes, ranges):
        self.mutexes = mutexes
        self.ranges = ranges  # [(start, end)] positions where held

    def holds(self, pos: int, mutex: str) -> bool:
        return mutex in self.mutexes and \
            any(a <= pos <= b for a, b in self.ranges)


def _last_ident(expr: str):
    expr = expr.strip().rstrip(")")
    m = _LAST_IDENT_RE.search(expr)
    return m.group(1) if m else None


def _lock_scopes(stripped: str, blocks: _Blocks):
    scopes = []
    for m in _LOCK_DECL_RE.finditer(stripped):
        kind, var, args = m.group(1), m.group(2), m.group(3)
        mutexes = {i for i in
                   (_last_ident(a) for a in args.split(","))
                   if i}
        if not mutexes:
            continue
        end = blocks.block_end(m.start())
        if kind == "unique_lock":
            # cut the held range at lk.unlock(), resume at lk.lock()
            ranges = []
            held_from = m.end()
            pos = m.end()
            pat = re.compile(r"\b%s\s*\.\s*(un)?lock\s*\(" % re.escape(var))
            for call in pat.finditer(stripped, m.end(), end):
                if call.group(1):  # unlock
                    if held_from is not None:
                        ranges.append((held_from, call.start()))
                        held_from = None
                else:  # lock
                    if held_from is None:
                        held_from = call.end()
                pos = call.end()
            del pos
            if held_from is not None:
                ranges.append((held_from, end))
        else:
            ranges = [(m.end(), end)]
        scopes.append(_LockScope(mutexes, ranges))
    return scopes


def _parse_annotations(source: str):
    """{member: mutex} for GUARDED_BY lines, plus the set of annotated
    declaration line numbers (excluded from the access scan)."""
    guarded = {}
    decl_lines = set()
    for lineno, line in enumerate(source.splitlines(), start=1):
        g = _GUARDED_RE.search(line)
        if not g:
            continue
        code = line[:g.start()]
        dm = _MEMBER_DECL_RE.search(code.rstrip())
        if dm:
            guarded[dm.group(1)] = g.group(1)
            decl_lines.add(lineno)
    return guarded, decl_lines


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _access_iter(stripped: str, member: str):
    """Positions where ``member`` is accessed: ``.member`` / ``->member``
    always; bare ``member`` too when it carries the trailing-underscore
    member naming convention."""
    pat = re.compile(r"(?:(?:\.|->)\s*|\b)(%s)\b" % re.escape(member)) \
        if member.endswith("_") else \
        re.compile(r"(?:\.|->)\s*(%s)\b" % re.escape(member))
    for m in pat.finditer(stripped):
        # skip declarations/annotations lines are handled by caller;
        # skip member-function definitions ``Type Class::member(...)``
        yield m.start(1)


def _pair_key(rel: str) -> str:
    base, _ext = os.path.splitext(rel)
    return base


def check_sources(sources: dict, atomic_sources: dict | None = None) -> list:
    """Lint {relpath: source}.  ``sources`` feeds the annotation rules
    (files are paired by basename); ``atomic_sources`` (default: the
    same mapping) feeds the memory-order rule."""
    findings = []
    atomic_sources = sources if atomic_sources is None else atomic_sources

    stripped = {rel: _strip(src) for rel, src in sources.items()}
    blocks = {rel: _Blocks(stripped[rel]) for rel in sources}
    locks = {rel: _lock_scopes(stripped[rel], blocks[rel])
             for rel in sources}
    suppressed = {rel: cpp_suppressed_rules(src)
                  for rel, src in sources.items()}

    # Annotations bind across a .hpp/.cpp pair.
    guarded_by_pair: dict[str, dict] = {}
    decl_lines: dict[str, set] = {}
    for rel, src in sources.items():
        guarded, decls = _parse_annotations(src)
        guarded_by_pair.setdefault(_pair_key(rel), {}).update(guarded)
        decl_lines[rel] = decls

    # -- guarded-member-unlocked -------------------------------------------
    for rel, src in sources.items():
        guarded = guarded_by_pair.get(_pair_key(rel), {})
        if not guarded:
            continue
        text = stripped[rel]
        for member, mutex in sorted(guarded.items()):
            for pos in _access_iter(text, member):
                line = _line_of(text, pos)
                if line in decl_lines[rel]:
                    continue
                if "guarded-member-unlocked" in \
                        suppressed[rel].get(line, ()):
                    continue
                funcs = blocks[rel].enclosing_functions(pos)
                if any(f.endswith("_locked") or f.endswith("_locked_")
                       for f in funcs):
                    continue
                if not funcs:
                    continue  # declaration scope, not executable code
                if any(s.holds(pos, mutex) for s in locks[rel]):
                    continue
                findings.append(Finding(
                    rel, line, "guarded-member-unlocked",
                    f"access to '{member}' (GUARDED_BY({mutex})) outside "
                    f"a lock_guard/unique_lock scope naming '{mutex}' "
                    f"and outside any *_locked function: take the lock, "
                    f"rename the function to the _locked convention, or "
                    f"carry an evidence-comment suppression"))

    # -- unannotated-mutex --------------------------------------------------
    for rel, src in sources.items():
        guarded = guarded_by_pair.get(_pair_key(rel), {})
        text = stripped[rel]
        for m in _MUTEX_MEMBER_RE.finditer(text):
            name = m.group(1)
            line = _line_of(text, m.start())
            if "unannotated-mutex" in suppressed[rel].get(line, ()):
                continue
            if blocks[rel].enclosing_functions(m.start()):
                continue  # function-local mutex, not a shared member
            if name in guarded.values():
                continue
            findings.append(Finding(
                rel, line, "unannotated-mutex",
                f"std::mutex member '{name}' with no GUARDED_BY({name}) "
                f"annotation on any member it protects: write the "
                f"sharing story down so the checker (and the reviewer) "
                f"can hold it"))

    # -- atomic-missing-order ----------------------------------------------
    for rel, src in atomic_sources.items():
        text = stripped.get(rel)
        if text is None:
            text = _strip(src)
        sup = suppressed.get(rel)
        if sup is None:
            sup = cpp_suppressed_rules(src)
        for m in _ATOMIC_OP_RE.finditer(text):
            # argument list with paren matching
            depth, j = 1, m.end()
            while j < len(text) and depth:
                if text[j] == "(":
                    depth += 1
                elif text[j] == ")":
                    depth -= 1
                j += 1
            args = text[m.end():j - 1]
            if "memory_order" in args:
                continue
            line = _line_of(text, m.start())
            if "atomic-missing-order" in sup.get(line, ()):
                continue
            findings.append(Finding(
                rel, line, "atomic-missing-order",
                f".{m.group(1)}() without an explicit std::memory_order "
                f"argument: state the ordering claim at the site "
                f"(relaxed for flags polled in loops, acq_rel for "
                f"join counters that publish data) — the trace-flag "
                f"load in common/log.cpp is the exemplar"))

    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def check(root: str, targets=DEFAULT_TARGETS, atomic_root=ATOMIC_ROOT) -> list:
    from .common import read_source

    sources = {}
    for rel in targets:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        sources[rel] = read_source(path)
    atomic_sources = dict(sources)
    base = os.path.join(root, atomic_root)
    if os.path.isdir(base):
        for dirpath, _dirnames, filenames in os.walk(base):
            for f in sorted(filenames):
                if not f.endswith((".cpp", ".hpp", ".h")):
                    continue
                path = os.path.join(dirpath, f)
                rel = os.path.relpath(path, root)
                atomic_sources.setdefault(rel, read_source(path))
    return check_sources(sources, atomic_sources)

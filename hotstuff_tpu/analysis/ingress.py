"""graftlint bounded-ingress checker: every enqueue onto a scheduler- or
mempool-facing queue in the graftsurge modules must route through the
admission controller.

The whole point of graftsurge is that NOTHING enters the verify
scheduler's class queues (or their harness-side models) without passing
the admission policy — byte/record caps, the overlap-driven bulk
derate, bulk-before-latency shedding.  One helper that appends to
``self.items`` directly would silently bypass all of it: the queue
would still look bounded in review, and the first overload would show
an admitted backlog the caps never saw.  This rule makes that bypass a
lint finding instead of a production incident.

Rule:
  bounded-ingress   a ``.append`` / ``.appendleft`` / ``.put`` /
                    ``.put_nowait`` call whose receiver is a
                    queue-carrying attribute (``items`` / ``queue`` /
                    ``queues`` / ``backlog`` / ``pending`` /
                    ``outbox``) in a surge module, OUTSIDE an admission
                    scope.  Admission scopes are the queue's own
                    ``offer`` / ``_offer_locked`` methods and any
                    method of ``AdmissionController`` — the audited
                    places where cap checks live.

Receiver detection is name-based like the sockets rule: the surge
modules use these conventional names for their admission-guarded
queues, and a rename that dodges the rule is exactly the edit a
reviewer should see.  Internal bookkeeping containers (the load
generator's arrival heap, telemetry rings) use other names and stay out
of scope by construction.  Inline ``# graftlint: disable=bounded-ingress``
suppressions follow the standard policy (analysis/README.md): only with
a worked justification.
"""

from __future__ import annotations

import ast
import os

from .common import Finding, apply_suppressions, parse_source, \
    read_source

# The graftsurge modules: scheduler-side admission and the harness-side
# load model that exercises it.
DEFAULT_TARGETS = (
    "hotstuff_tpu/sidecar/sched",
    "hotstuff_tpu/harness/loadgen.py",
)

_ENQUEUE_OPS = {"append", "appendleft", "put", "put_nowait"}
_QUEUE_NAMES = {"items", "queue", "queues", "backlog", "pending",
                "outbox"}
_ADMISSION_FUNCS = {"offer", "_offer_locked"}
_ADMISSION_CLASSES = {"AdmissionController"}


def _queue_receiver(node: ast.AST):
    """Rightmost queue-ish identifier of an enqueue receiver
    (``self.items.append`` -> ``items``; ``self._queues[cls].put`` ->
    ``_queues``), else None.  Attribute receivers only: a bare local
    list that happens to be named ``items`` is function-private state,
    not a shared queue anything could bypass admission into."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Subscript):
        return _queue_receiver(node.value)
    else:
        return None
    if name.lstrip("_") in _QUEUE_NAMES:
        return name
    return None


def _walk_with_context(tree: ast.Module):
    """Yield ``(node, func_name, class_name)`` with the nearest
    enclosing function and class tracked."""
    def visit(node, func, cls):
        for child in ast.iter_child_nodes(node):
            child_func, child_cls = func, cls
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            elif isinstance(child, ast.ClassDef):
                child_cls = child.name
            yield child, child_func, child_cls
            yield from visit(child, child_func, child_cls)

    yield from visit(tree, None, None)


def _check_source(rel: str, source: str) -> list:
    findings = []
    tree = parse_source(source)
    for node, func, cls in _walk_with_context(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _ENQUEUE_OPS):
            continue
        queue = _queue_receiver(fn.value)
        if queue is None:
            continue
        if func in _ADMISSION_FUNCS or cls in _ADMISSION_CLASSES:
            continue
        where = f"{cls}.{func}" if cls and func else (func or cls or
                                                      "<module>")
        findings.append(Finding(
            rel, node.lineno, "bounded-ingress",
            f"{where} enqueues onto {queue!r} via .{fn.attr}() outside "
            "the admission controller: surge-module queues admit only "
            "through offer/_offer_locked (or AdmissionController "
            "methods) so the byte caps, bulk derate, and "
            "bulk-before-latency policy can never be bypassed"))
    return findings


def _iter_targets(root: str, targets):
    for rel in targets:
        path = os.path.join(root, rel)
        if os.path.isfile(path):
            yield rel, path
        elif os.path.isdir(path):
            for dirpath, _dirnames, filenames in os.walk(path):
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        yield os.path.relpath(full, root), full


def check(root: str, targets=DEFAULT_TARGETS) -> list:
    findings = []
    sources = {}
    for rel, path in _iter_targets(root, targets):
        try:
            source = read_source(path)
        except OSError:
            continue
        sources[rel] = source
        try:
            findings += _check_source(rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rel, e.lineno or 1, "bounded-ingress",
                f"cannot parse module: {e.msg}"))
    return apply_suppressions(findings, sources)

"""Per-fault-class recovery SLOs: pass/fail verdicts over the recovery
summary instead of the bare "commits resume" assertion.

A fault class is the target kind plus the action (``node-kill``,
``sidecar-degrade``, ``link-heal``, ...), and the SLO is the maximum
recovery latency — first commit after the event — the class is allowed
to cost.  ``judge`` turns ``summarize_recovery`` output into per-event
verdicts the LogParser surfaces as notes (and raises on, under the
strict testbed assertion) and bench.py folds into the ``chaos``
headline, so "recovered" always means "recovered fast enough", not
merely "eventually".

Defaults are deliberately generous multiples of the local testbed's
view-change budget (timeout_delay defaults to 5 s and a kill can
legitimately cost a couple of view changes plus the node-side circuit
breaker's probe backoff); deployments with tighter targets override
per class via ``--slo`` (file / dict / inline ``"node-kill=8000;
link-heal=3000"``).
"""

from __future__ import annotations

import json
import os
import re

from .plan import LEADER_CASCADE, SIDECAR, client_index, link_name, \
    node_index, sidecar_index

# class -> max recovery_ms (the table --slo overlays).
DEFAULT_SLO_MS = {
    "node-kill": 30_000.0,
    "node-restart": 20_000.0,
    "node-pause": 30_000.0,
    "node-resume": 20_000.0,
    "sidecar-kill": 15_000.0,
    "sidecar-restart": 15_000.0,
    "sidecar-degrade": 10_000.0,
    # graftguard: a scripted launch wedge rides the in-sidecar
    # supervisor — host-fallback replies keep consensus committing
    # immediately, so the budget covers one ladder execution plus the
    # async crash-only reboot's BUSY window, not a breaker timeout.
    "sidecar-wedge": 20_000.0,
    # graftfleet: killing ONE endpoint of a --sidecar-fleet run must
    # re-home verify traffic to the next healthy sidecar — an in-flight
    # resubmit plus at most a breaker trip, nowhere near the
    # single-sidecar kill's breaker-then-host-path budget.  The parser's
    # strict companion assertion (zero host-path verifies while a
    # healthy secondary exists) rides on the same events.
    "sidecar-failover": 10_000.0,
    "link-partition": 30_000.0,
    "link-heal": 20_000.0,
    # graftsurge: a flash crowd ends at t + for; the system must be back
    # at its pre-surge baseline within this budget of the window CLOSING
    # (the commit-scalar verdict measures from the injection like every
    # other class; the metrics verdict below measures from the end).
    "client-surge": 30_000.0,
    # graftview: a leader-cascade kill k drill — k chained view changes,
    # each costing one backed-off timeout (default schedule: 5 s, 10 s,
    # 20 s, ... capped) plus batched TC assembly, before a live leader
    # proposes.  The budget covers a depth-3 cascade under the default
    # pacemaker; deeper drills override per run.
    "view-change": 60_000.0,
}

# Metrics-driven recovery-to-baseline defaults (judge_baseline_recovery):
# the pre-event baseline is the median sampled throughput over this
# window before the event, and "recovered" means the sampled curve is
# back to at least this fraction of it.
BASELINE_WINDOW_S = 10.0
BASELINE_FRACTION = 0.7
# Fewer good samples than this before the event -> not judged (a verdict
# off two points would be noise presented as policy).
BASELINE_MIN_SAMPLES = 3


class SloError(ValueError):
    """Malformed SLO table spec."""


def fault_class(event: dict) -> str:
    """Executed-event dict (PlanRunner.events shape) -> fault class."""
    target = str(event.get("target", ""))
    if target == LEADER_CASCADE:
        # The drill IS the view change: one class regardless of action,
        # per the graftview acceptance grammar.
        return "view-change"
    if target == SIDECAR or sidecar_index(target) is not None:
        # graftfleet: a kill aimed at ONE indexed endpoint is judged as
        # a failover (re-home to the next healthy sidecar), not as the
        # single-sidecar kill class (breaker-then-host-path budget).
        if sidecar_index(target) is not None and \
                event.get("action") == "kill":
            return "sidecar-failover"
        kind = "sidecar"
    elif node_index(target) is not None:
        kind = "node"
    elif link_name(target) is not None:
        kind = "link"
    elif client_index(target) is not None:
        kind = "client"
    else:
        kind = "unknown"
    return f"{kind}-{event.get('action')}"


def event_window_end(event: dict) -> float | None:
    """Wall time a fault's ACTIVE window closes: the injection stamp,
    plus the surge duration for surge events (recovery-to-baseline is
    only meaningful once the extra load is gone).  The surge duration
    default is plan.surge_window_s — the SAME default the validator and
    the injector apply, so an omitted ``for`` means one thing at every
    layer."""
    from .plan import surge_window_s

    wall = event.get("wall")
    if not isinstance(wall, (int, float)):
        return None
    end = float(wall)
    if event.get("action") == "surge":
        end += surge_window_s(event.get("params"))
    return end


def throughput_series(samples) -> list:
    """Sampled OP_STATS series (obs/sampler.py JSONL records) ->
    ``[(t, sigs_per_s)]`` from consecutive good samples' cumulative
    ``sigs_launched`` deltas.  A sidecar restart resets the counter —
    a negative delta clamps to 0 (an honest gap) rather than poisoning
    the curve."""
    good = [(s["t"], s["stats"].get("sigs_launched"))
            for s in samples
            if s.get("ok") and isinstance(s.get("stats"), dict)
            and isinstance(s["stats"].get("sigs_launched"), (int, float))]
    out = []
    for (t0, v0), (t1, v1) in zip(good, good[1:]):
        dt = t1 - t0
        if dt <= 0:
            continue
        out.append((t1, max(0.0, (v1 - v0)) / dt))
    return out


def judge_baseline_recovery(samples, events, slos: dict | None = None,
                            window_s: float = BASELINE_WINDOW_S,
                            fraction: float = BASELINE_FRACTION) -> dict:
    """Metrics-driven recovery verdicts (the PR 7 follow-up): judge each
    fault off the SAMPLED throughput curve returning to its pre-event
    baseline, not just off the first commit after the injection.

    Per event: baseline = median throughput over ``window_s`` before the
    injection; the event recovers when the curve first reaches
    ``fraction`` x baseline AFTER the event's active window closes
    (surges: after t + for).  The recovery budget is the event's fault
    class SLO from the same table ``judge`` uses.  Events without
    enough pre-event telemetry are reported ``judged: false`` and do
    not fail the run — absence of evidence is surfaced, not punished.

    Returns ``{"verdicts": [...], "ok": bool, "judged": int}``.
    """
    from statistics import median

    table = parse_slos(None)
    if slos:
        table.update(slos)
    series = throughput_series(samples)
    verdicts = []
    judged = 0
    for e in events:
        cls = fault_class(e)
        wall = e.get("wall")
        end = event_window_end(e)
        v = {"label": f"t={e.get('t')}s {e.get('action')} "
                      f"{e.get('target')}", "class": cls,
             "judged": False, "ok": True,
             "baseline_sigs_per_s": None, "recovered_ms": None}
        if wall is None or end is None:
            v["reason"] = "no wall stamp"
            verdicts.append(v)
            continue
        base_pts = [r for t, r in series if wall - window_s <= t < wall]
        if len(base_pts) < BASELINE_MIN_SAMPLES:
            v["reason"] = (f"insufficient pre-event telemetry "
                           f"({len(base_pts)} sample(s))")
            verdicts.append(v)
            continue
        baseline = median(base_pts)
        v["baseline_sigs_per_s"] = round(baseline, 1)
        if baseline <= 0:
            v["reason"] = "pre-event baseline is zero"
            verdicts.append(v)
            continue
        slo_ms = table.get(cls)
        target = fraction * baseline
        recovered_ms = None
        for t, r in series:
            if t > end and r >= target:
                recovered_ms = round((t - end) * 1e3, 1)
                break
        if recovered_ms is None:
            # Fail only when the sampled series actually COVERS the
            # recovery budget: a run whose sampler stopped before the
            # SLO elapsed gave the event no fair chance — that is
            # absence of evidence (surfaced, unjudged), not a breach.
            last_t = series[-1][0]
            horizon = end + (slo_ms / 1e3 if slo_ms else 0.0)
            if last_t < horizon:
                v["reason"] = ("sampled series ends "
                               f"{(horizon - last_t):.1f} s before the "
                               "recovery budget elapsed")
                verdicts.append(v)
                continue
        judged += 1
        v["judged"] = True
        v["recovered_ms"] = recovered_ms
        v["slo_ms"] = slo_ms
        if recovered_ms is None:
            v.update(ok=False,
                     reason=f"throughput never returned to "
                            f"{fraction:.0%} of baseline "
                            f"({target:.1f} sigs/s)")
        elif slo_ms is not None and recovered_ms > slo_ms:
            v.update(ok=False,
                     reason=f"baseline recovery {recovered_ms:g} ms > "
                            f"SLO {slo_ms:g} ms")
        else:
            v["reason"] = ""
        verdicts.append(v)
    return {
        "verdicts": verdicts,
        "ok": all(v["ok"] for v in verdicts),
        "judged": judged,
    }


def parse_slos(spec) -> dict:
    """Full SLO table (defaults overlaid with the spec's overrides) from
    None / a dict / a JSON file path / an inline ``"class=ms;..."``
    string.  Unknown classes and non-positive values fail here, not as a
    silently never-matching verdict."""
    table = dict(DEFAULT_SLO_MS)
    if spec is None:
        return table
    if isinstance(spec, str):
        if os.path.isfile(spec):
            try:
                with open(spec, encoding="utf-8") as f:
                    spec = json.load(f)
            except (OSError, ValueError) as e:
                raise SloError(f"cannot read SLO table {spec!r}: {e}")
        else:
            entries = [e for e in re.split(r"[;\n]", spec) if e.strip()]
            if not entries:
                raise SloError("empty SLO spec")
            parsed = {}
            for entry in entries:
                if "=" not in entry:
                    raise SloError(f"bad SLO entry {entry!r} "
                                   "(want class=ms)")
                k, v = entry.split("=", 1)
                parsed[k.strip()] = v.strip()
            spec = parsed
    if not isinstance(spec, dict):
        raise SloError(f"unsupported SLO spec type {type(spec).__name__}")
    for cls, raw in spec.items():
        if cls not in DEFAULT_SLO_MS:
            raise SloError(
                f"unknown fault class {cls!r} (have "
                f"{', '.join(sorted(DEFAULT_SLO_MS))})")
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            raise SloError(f"SLO for {cls} must be a number (got {raw!r})")
        if not ms > 0 or ms != ms or ms == float("inf"):
            raise SloError(f"SLO for {cls} must be finite > 0 (got {ms:g})")
        table[cls] = ms
    return table


def judge(summary: dict, slos: dict | None = None) -> dict:
    """``summarize_recovery`` output + SLO table -> JSON-safe verdicts::

        {"verdicts": [{"label", "class", "recovery_ms", "slo_ms",
                       "ok", "reason"}, ...],
         "ok": bool,                 # every event inside its SLO
         "worst_headroom_ms": float} # min(slo - recovery); negative = miss

    A failed injection or an unrecovered event fails its verdict (an SLO
    cannot be met by a fault that never resolved), so ``ok`` subsumes
    the old bare liveness assertion.
    """
    from .recovery import event_label

    table = parse_slos(None)
    if slos:
        table.update(slos)
    verdicts = []
    worst = None
    for e in summary.get("events", []):
        cls = fault_class(e)
        slo_ms = table.get(cls)
        v = {"label": event_label(e), "class": cls,
             "recovery_ms": e.get("recovery_ms"), "slo_ms": slo_ms}
        if slo_ms is None:
            v.update(ok=False, reason=f"no SLO for class {cls!r}")
        elif not e.get("ok", True):
            v.update(ok=False, reason="injection failed")
        elif not e.get("recovered"):
            v.update(ok=False, reason="no commit after event")
        else:
            headroom = slo_ms - e["recovery_ms"]
            worst = headroom if worst is None else min(worst, headroom)
            v.update(ok=e["recovery_ms"] <= slo_ms,
                     reason="" if e["recovery_ms"] <= slo_ms else
                     f"recovery {e['recovery_ms']:g} ms > SLO "
                     f"{slo_ms:g} ms")
        verdicts.append(v)
    return {
        "verdicts": verdicts,
        "ok": all(v["ok"] for v in verdicts),
        "worst_headroom_ms": worst if worst is not None else 0.0,
    }

"""Per-fault-class recovery SLOs: pass/fail verdicts over the recovery
summary instead of the bare "commits resume" assertion.

A fault class is the target kind plus the action (``node-kill``,
``sidecar-degrade``, ``link-heal``, ...), and the SLO is the maximum
recovery latency — first commit after the event — the class is allowed
to cost.  ``judge`` turns ``summarize_recovery`` output into per-event
verdicts the LogParser surfaces as notes (and raises on, under the
strict testbed assertion) and bench.py folds into the ``chaos``
headline, so "recovered" always means "recovered fast enough", not
merely "eventually".

Defaults are deliberately generous multiples of the local testbed's
view-change budget (timeout_delay defaults to 5 s and a kill can
legitimately cost a couple of view changes plus the node-side circuit
breaker's probe backoff); deployments with tighter targets override
per class via ``--slo`` (file / dict / inline ``"node-kill=8000;
link-heal=3000"``).
"""

from __future__ import annotations

import json
import os
import re

from .plan import SIDECAR, link_name, node_index

# class -> max recovery_ms (the table --slo overlays).
DEFAULT_SLO_MS = {
    "node-kill": 30_000.0,
    "node-restart": 20_000.0,
    "node-pause": 30_000.0,
    "node-resume": 20_000.0,
    "sidecar-kill": 15_000.0,
    "sidecar-restart": 15_000.0,
    "sidecar-degrade": 10_000.0,
    "link-partition": 30_000.0,
    "link-heal": 20_000.0,
}


class SloError(ValueError):
    """Malformed SLO table spec."""


def fault_class(event: dict) -> str:
    """Executed-event dict (PlanRunner.events shape) -> fault class."""
    target = str(event.get("target", ""))
    if target == SIDECAR:
        kind = "sidecar"
    elif node_index(target) is not None:
        kind = "node"
    elif link_name(target) is not None:
        kind = "link"
    else:
        kind = "unknown"
    return f"{kind}-{event.get('action')}"


def parse_slos(spec) -> dict:
    """Full SLO table (defaults overlaid with the spec's overrides) from
    None / a dict / a JSON file path / an inline ``"class=ms;..."``
    string.  Unknown classes and non-positive values fail here, not as a
    silently never-matching verdict."""
    table = dict(DEFAULT_SLO_MS)
    if spec is None:
        return table
    if isinstance(spec, str):
        if os.path.isfile(spec):
            try:
                with open(spec, encoding="utf-8") as f:
                    spec = json.load(f)
            except (OSError, ValueError) as e:
                raise SloError(f"cannot read SLO table {spec!r}: {e}")
        else:
            entries = [e for e in re.split(r"[;\n]", spec) if e.strip()]
            if not entries:
                raise SloError("empty SLO spec")
            parsed = {}
            for entry in entries:
                if "=" not in entry:
                    raise SloError(f"bad SLO entry {entry!r} "
                                   "(want class=ms)")
                k, v = entry.split("=", 1)
                parsed[k.strip()] = v.strip()
            spec = parsed
    if not isinstance(spec, dict):
        raise SloError(f"unsupported SLO spec type {type(spec).__name__}")
    for cls, raw in spec.items():
        if cls not in DEFAULT_SLO_MS:
            raise SloError(
                f"unknown fault class {cls!r} (have "
                f"{', '.join(sorted(DEFAULT_SLO_MS))})")
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            raise SloError(f"SLO for {cls} must be a number (got {raw!r})")
        if not ms > 0 or ms != ms or ms == float("inf"):
            raise SloError(f"SLO for {cls} must be finite > 0 (got {ms:g})")
        table[cls] = ms
    return table


def judge(summary: dict, slos: dict | None = None) -> dict:
    """``summarize_recovery`` output + SLO table -> JSON-safe verdicts::

        {"verdicts": [{"label", "class", "recovery_ms", "slo_ms",
                       "ok", "reason"}, ...],
         "ok": bool,                 # every event inside its SLO
         "worst_headroom_ms": float} # min(slo - recovery); negative = miss

    A failed injection or an unrecovered event fails its verdict (an SLO
    cannot be met by a fault that never resolved), so ``ok`` subsumes
    the old bare liveness assertion.
    """
    from .recovery import event_label

    table = parse_slos(None)
    if slos:
        table.update(slos)
    verdicts = []
    worst = None
    for e in summary.get("events", []):
        cls = fault_class(e)
        slo_ms = table.get(cls)
        v = {"label": event_label(e), "class": cls,
             "recovery_ms": e.get("recovery_ms"), "slo_ms": slo_ms}
        if slo_ms is None:
            v.update(ok=False, reason=f"no SLO for class {cls!r}")
        elif not e.get("ok", True):
            v.update(ok=False, reason="injection failed")
        elif not e.get("recovered"):
            v.update(ok=False, reason="no commit after event")
        else:
            headroom = slo_ms - e["recovery_ms"]
            worst = headroom if worst is None else min(worst, headroom)
            v.update(ok=e["recovery_ms"] <= slo_ms,
                     reason="" if e["recovery_ms"] <= slo_ms else
                     f"recovery {e['recovery_ms']:g} ms > SLO "
                     f"{slo_ms:g} ms")
        verdicts.append(v)
    return {
        "verdicts": verdicts,
        "ok": all(v["ok"] for v in verdicts),
        "worst_headroom_ms": worst if worst is not None else 0.0,
    }

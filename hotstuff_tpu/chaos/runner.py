"""Plan execution: fire each event at its offset, record what happened.

The runner owns a daemon thread so the harness's duration sleep is the
only clock the bench itself keeps; injector failures are *recorded*
(``ok: false`` + error text), never raised — a fault plan that trips
over its own injection must still let the bench finish, tear down, and
surface the failure through the parsed summary (the LogParser treats a
failed injection as a hard error there).

The clock/sleep/wall callables are injectable: tests and bench.py's
headline probe drive a plan through a virtual clock in microseconds;
the harness uses the real ones.
"""

from __future__ import annotations

import threading
from time import monotonic, sleep as _real_sleep, time as _wall_clock

from .plan import FaultPlan

# Sleep in short slices so stop() is observed promptly even mid-wait.
_MAX_SLICE_S = 0.2


class PlanRunner:
    def __init__(self, plan: FaultPlan, injector, clock=monotonic,
                 sleep=_real_sleep, wall=_wall_clock):
        self._plan = plan
        self._injector = injector
        self._clock = clock
        self._sleep = sleep
        self._wall = wall
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._executed: list[dict] = []
        self._lock = threading.Lock()

    def start(self, t0: float | None = None):
        """Begin executing; event times are offsets from ``t0`` (default:
        now)."""
        assert self._thread is None, "runner already started"
        base = self._clock() if t0 is None else t0
        self._thread = threading.Thread(
            target=self._run, args=(base,), daemon=True, name="chaos-runner")
        self._thread.start()

    def stop(self):
        """Skip any not-yet-due events (run window over)."""
        self._stop.set()

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    def events(self) -> list:
        """Executed events (JSON-safe dicts): the plan fields plus the
        wall-clock ``wall`` stamp recovery latency is measured from, and
        ``ok``/``error`` for the injection itself.  Skipped events (a
        stop() before their time) are absent."""
        with self._lock:
            return [dict(e) for e in self._executed]

    def all_ok(self) -> bool:
        with self._lock:
            return all(e["ok"] for e in self._executed)

    # -- internals ----------------------------------------------------------

    def _run(self, base: float):
        for event in self._plan.events:
            due = base + event.t
            while not self._stop.is_set():
                left = due - self._clock()
                if left <= 0:
                    break
                self._sleep(min(left, _MAX_SLICE_S))
            if self._stop.is_set():
                return
            record = event.to_json()
            # The wall stamp is taken BEFORE the injection so recovery
            # latency includes the injection's own cost (a sidecar
            # restart's boot time is part of what the fault costs).
            record["wall"] = self._wall()
            try:
                self._injector.apply(event)
                record["ok"] = True
            except Exception as e:  # noqa: BLE001 — recorded, never raised
                record["ok"] = False
                record["error"] = f"{e!r:.200}"
            with self._lock:
                self._executed.append(record)

"""Per-fault recovery latency from executed events + the commit timeline.

Recovery of a fault is the first commit (merged earliest-commit view
across the committee, the LogParser's ``commits`` map) strictly after
the event's wall-clock injection stamp: HotStuff's liveness argument
promises exactly that commits resume after the view-change timeout, so
the gap between the injection and the next commit *is* the price of the
fault.  Every event is measured — including restarts/resumes — because
re-integration has its own recovery cost (a rebooting replica can steal
a leader slot and force another view change).

Shared by the harness LogParser (run-summary notes + strict assertion)
and bench.py's ``chaos`` headline field, so the two never disagree on
what "recovered" means.
"""

from __future__ import annotations

from bisect import bisect_right


def summarize_recovery(events, commit_times) -> dict:
    """``events``: executed-event dicts (PlanRunner.events() shape, or the
    ``logs/chaos-events.json`` round trip).  ``commit_times``: iterable of
    posix commit timestamps.  Returns a JSON-safe summary::

        {"events": [{t, target, action, wall, ok, recovery_ms,
                     recovered}, ...],
         "recovered": bool,        # every event saw a later commit
         "injected_ok": bool,      # every injection itself succeeded
         "max_recovery_ms": float,
         "unrecovered": [labels]}
    """
    commits = sorted(float(t) for t in commit_times)
    out_events = []
    unrecovered = []
    injected_ok = True
    max_ms = 0.0
    for e in events:
        rec = {
            "t": e.get("t"),
            "target": e.get("target"),
            "action": e.get("action"),
            "wall": e.get("wall"),
            "ok": bool(e.get("ok", True)),
        }
        if e.get("params"):
            rec["params"] = e["params"]
        if not rec["ok"]:
            injected_ok = False
            rec["error"] = e.get("error", "injection failed")
        wall = rec["wall"]
        recovery_ms = None
        if wall is not None and commits:
            i = bisect_right(commits, float(wall))
            if i < len(commits):
                recovery_ms = round((commits[i] - float(wall)) * 1e3, 1)
        rec["recovery_ms"] = recovery_ms
        rec["recovered"] = recovery_ms is not None
        if not rec["recovered"]:
            unrecovered.append(event_label(rec))
        else:
            max_ms = max(max_ms, recovery_ms)
        out_events.append(rec)
    return {
        "events": out_events,
        "recovered": not unrecovered,
        "injected_ok": injected_ok,
        "max_recovery_ms": max_ms,
        "unrecovered": unrecovered,
    }


def event_label(rec: dict) -> str:
    """One spelling for an event across the summary: the 'unrecovered'
    list here and the LogParser's per-event Chaos notes both use it."""
    t = rec.get("t")
    t_str = f"t={t:g}s" if isinstance(t, (int, float)) else "t=?"
    return f"{t_str} {rec.get('action')} {rec.get('target')}"

"""graftwan link shaping: declarative per-host-pair WAN specs, compiled
to ``tc qdisc netem`` for remote fleets, with a root-free userspace TCP
proxy fallback so local and CI runs exercise the identical plan schema.

The reference's headline artifact is a 5-region matrix (SURVEY.md §3.5 /
§6); HotStuff's responsiveness claim only means something under measured
WAN latency.  A WAN spec names directed links between committee
endpoints and the shape of each:

Endpoints
    ``node:<i>``   replica i (boot-order index locally, host index on a
                   fleet)
    ``sidecar``    the shared verify sidecar (shaping this link models a
                   slow or partially partitioned accelerator service)
    ``client``     the load generators
    ``*``          wildcard source — every other endpoint (src only)

Shape fields (any subset; a shapeless link is legal — it exists purely
as a ``link:<name>`` partition target for fault plans)
    ``latency_ms``  one-way added delay          ``jitter_ms`` +- spread
    ``loss_pct``    loss percentage (0..100)     ``rate_mbit`` rate cap

Links are DIRECTED (``src>dst``): an asymmetric spec — e.g. node:0 can
reach the sidecar but not vice versa — models the partial partitions of
a shared sidecar that symmetric netem recipes cannot express.  An
optional ``default`` shape applies to every host pair without an
explicit link on remote fleets.

Two executors, one schema:

* ``tc_setup_commands`` compiles the spec into per-host ``tc`` command
  lists (root prio qdisc, one netem band + dst-ip u32 filter per link)
  for the ssh transport; ``tc_partition_commands``/``tc_heal_commands``
  drive mid-run ``link:<name>`` fault-plan events via ``netem loss
  100%`` and a restore of the spec shape.
* ``WanProxy`` is the root-free fallback: a threaded TCP proxy applying
  delay/jitter/loss/rate per forwarded chunk, with ``partition()`` /
  ``heal()`` for the same plan events.  Loss on a byte stream cannot
  drop single segments (TCP would just retransmit), so a lossy chunk
  drops the CONNECTION — the visible failure mode loss actually causes
  a consensus link (stalled TCP, reconnect) — and rate is enforced by
  sleeping the pump to the token rate.
"""

from __future__ import annotations

import json
import os
import random
import re
import socket
import threading
import time
from dataclasses import dataclass, field

NODE_RE = re.compile(r"^node:(\d+)$")
SIDECAR = "sidecar"
CLIENT = "client"
WILDCARD = "*"

# The first prio band free for netem attachment: bands 1..3 are the
# default priomap's, per-link bands count up from here.  The prio
# qdisc hard-caps at 16 bands, so one host's egress can carry at most
# 16 - 3 shaped links — enforced at compile time (host_links), which
# runs in the remote pre-flight before any host boots.
_FIRST_BAND = 4
_MAX_BANDS = 16


class WanError(ValueError):
    """Malformed or physically unrealizable WAN spec."""


def _endpoint_ok(ep: str, allow_wildcard=False) -> bool:
    if ep == WILDCARD:
        return allow_wildcard
    return ep in (SIDECAR, CLIENT) or NODE_RE.match(ep) is not None


@dataclass(frozen=True)
class LinkShape:
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    loss_pct: float = 0.0
    rate_mbit: float = 0.0   # 0 = uncapped

    def validate(self, label: str):
        for key in ("latency_ms", "jitter_ms", "loss_pct", "rate_mbit"):
            v = getattr(self, key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v < 0 or v == float("inf"):
                raise WanError(f"{label}: {key} must be a finite number "
                               f">= 0 (got {v!r})")
        if self.loss_pct > 100:
            raise WanError(f"{label}: loss_pct must be <= 100")
        if self.jitter_ms and not self.latency_ms:
            raise WanError(f"{label}: jitter_ms needs latency_ms")

    def is_noop(self) -> bool:
        return not (self.latency_ms or self.loss_pct or self.rate_mbit)

    def to_json(self) -> dict:
        return {k: v for k, v in (
            ("latency_ms", self.latency_ms), ("jitter_ms", self.jitter_ms),
            ("loss_pct", self.loss_pct), ("rate_mbit", self.rate_mbit)) if v}


@dataclass(frozen=True)
class Link:
    src: str
    dst: str
    shape: LinkShape
    name: str = ""

    def label(self) -> str:
        return self.name or f"{self.src}>{self.dst}"

    def to_json(self) -> dict:
        out = {"src": self.src, "dst": self.dst, **self.shape.to_json()}
        if self.name:
            out["name"] = self.name
        return out


@dataclass(frozen=True)
class WanSpec:
    links: tuple = ()
    default: LinkShape | None = None

    def by_name(self, name: str):
        for link in self.links:
            if link.label() == name:
                return link
        return None

    def link_names(self) -> list:
        return [link.label() for link in self.links]

    def to_json(self) -> dict:
        out = {"links": [link.to_json() for link in self.links]}
        if self.default is not None:
            out["default"] = self.default.to_json()
        return out


_SHAPE_KEYS = ("latency_ms", "jitter_ms", "loss_pct", "rate_mbit")


def _shape_from_dict(obj: dict, label: str) -> LinkShape:
    kwargs = {}
    for key in _SHAPE_KEYS:
        if key in obj:
            try:
                kwargs[key] = float(obj[key])
            except (TypeError, ValueError):
                raise WanError(f"{label}: {key} must be a number "
                               f"(got {obj[key]!r})")
    shape = LinkShape(**kwargs)
    shape.validate(label)
    return shape


def _link_from_dict(obj: dict) -> Link:
    unknown = set(obj) - {"src", "dst", "name", *_SHAPE_KEYS}
    if unknown:
        raise WanError(f"unknown link key(s) {sorted(unknown)}")
    try:
        src, dst = str(obj["src"]), str(obj["dst"])
    except KeyError as e:
        raise WanError(f"link needs 'src' and 'dst': missing {e}")
    name = str(obj.get("name", ""))
    label = name or f"{src}>{dst}"
    if not _endpoint_ok(src, allow_wildcard=True):
        raise WanError(f"{label}: bad src {src!r} (want node:<i>, "
                       "sidecar, client, or *)")
    if not _endpoint_ok(dst):
        raise WanError(f"{label}: bad dst {dst!r} (want node:<i>, "
                       "sidecar, or client)")
    if src == dst:
        raise WanError(f"{label}: src and dst must differ")
    return Link(src, dst, _shape_from_dict(obj, label), name)


def _link_from_text(entry: str) -> dict:
    """``"<src>><dst> [k=v ...]"`` / ``"default k=v ..."`` -> link dict
    (the inline DSL; returns dicts so file and DSL share validation)."""
    toks = entry.split()
    if not toks:
        raise WanError("empty WAN entry")
    out = {}
    if toks[0] == "default":
        out["__default__"] = True
    else:
        if ">" not in toks[0]:
            raise WanError(f"bad WAN entry {entry!r}: want "
                           "'<src>><dst> [k=v ...]' or 'default k=v ...'")
        src, _, dst = toks[0].partition(">")
        out["src"], out["dst"] = src, dst
    for tok in toks[1:]:
        if "=" not in tok:
            raise WanError(f"bad param {tok!r} in {entry!r} (want k=v)")
        k, v = tok.split("=", 1)
        out[k] = v
    return out


def parse_wan(spec) -> WanSpec:
    """Parse + validate a WAN spec from any accepted shape:

    * a ``WanSpec`` (returned as-is),
    * a dict: ``{"links": [...], "default": {...}}``,
    * a path to a JSON file of that dict (or a bare link list),
    * an inline DSL string: ``";"``/newline-separated entries like
      ``"node:0>node:1 latency_ms=200 loss_pct=0.5; *>sidecar
      latency_ms=20 name=sc; default latency_ms=50 jitter_ms=5"``.

    Raises :class:`WanError` on anything malformed.
    """
    if isinstance(spec, WanSpec):
        return spec
    if isinstance(spec, str):
        if os.path.isfile(spec):
            try:
                with open(spec, encoding="utf-8") as f:
                    spec = json.load(f)
            except (OSError, ValueError) as e:
                raise WanError(f"cannot read WAN spec {spec!r}: {e}")
        else:
            entries = [e for e in re.split(r"[;\n]", spec) if e.strip()]
            if not entries:
                raise WanError("empty WAN spec")
            parsed = [_link_from_text(e.strip()) for e in entries]
            spec = {"links": [p for p in parsed if "__default__" not in p]}
            defaults = [p for p in parsed if "__default__" in p]
            if len(defaults) > 1:
                raise WanError("more than one 'default' entry")
            if defaults:
                d = dict(defaults[0])
                d.pop("__default__")
                spec["default"] = d
    if isinstance(spec, (list, tuple)):
        spec = {"links": list(spec)}
    if not isinstance(spec, dict):
        raise WanError(f"unsupported WAN spec type {type(spec).__name__}")
    unknown = set(spec) - {"links", "default"}
    if unknown:
        raise WanError(f"unknown WAN spec key(s) {sorted(unknown)}")
    raw_links = spec.get("links", [])
    if not isinstance(raw_links, (list, tuple)):
        raise WanError("'links' must be a list")
    links = []
    for entry in raw_links:
        if not isinstance(entry, dict):
            raise WanError(f"bad link entry {entry!r}")
        links.append(_link_from_dict(entry))
    default = None
    if spec.get("default") is not None:
        if not isinstance(spec["default"], dict):
            raise WanError("'default' must be an object of shape fields")
        bad = set(spec["default"]) - set(_SHAPE_KEYS)
        if bad:
            raise WanError(f"default: unknown shape key(s) {sorted(bad)}")
        default = _shape_from_dict(spec["default"], "default")
    if not links and default is None:
        raise WanError("WAN spec shapes nothing (no links, no default)")
    seen = set()
    for link in links:
        if link.label() in seen:
            raise WanError(f"duplicate link {link.label()!r}")
        seen.add(link.label())
    # Two links covering the same (src-identity, dst) pair are
    # unrealizable: tc would install two same-priority filters for one
    # dst IP (only the first band ever carries traffic, the second
    # link's shape AND its partition/heal plan events silently no-op),
    # and the local WanProxy executor would chain proxies into a
    # topology the spec never declared.  Same dst + same src — or a
    # wildcard src, which expands to every other endpoint — overlaps.
    for i, a in enumerate(links):
        for b in links[i + 1:]:
            if a.dst == b.dst and (a.src == b.src or WILDCARD in
                                   (a.src, b.src)):
                raise WanError(
                    f"links {a.label()!r} and {b.label()!r} both shape "
                    f"traffic into {a.dst!r} from the same source: only "
                    "one would take effect")
    return WanSpec(tuple(links), default)


# ---------------------------------------------------------------------------
# tc/netem compilation (the root-ful remote executor)
# ---------------------------------------------------------------------------


def netem_args(shape: LinkShape) -> str:
    """netem option string for a shape (may be empty: no impairment)."""
    parts = []
    if shape.latency_ms:
        parts.append(f"delay {shape.latency_ms:g}ms")
        if shape.jitter_ms:
            parts.append(f"{shape.jitter_ms:g}ms")
    if shape.loss_pct:
        parts.append(f"loss {shape.loss_pct:g}%")
    if shape.rate_mbit:
        parts.append(f"rate {shape.rate_mbit:g}mbit")
    return " ".join(parts)


def host_links(spec: WanSpec, identity: str, peers: dict) -> list:
    """The directed links THIS host must shape on egress, in a
    deterministic order shared by setup and mid-run partition/heal:
    ``[(link, dst_ip, band)]``.  ``peers`` maps endpoint identities
    (``node:<i>``/``sidecar``) to IPs; the default shape fills every
    peer pair no explicit link covers."""
    out = []
    explicit_dsts = set()
    for link in spec.links:
        if link.src != identity and link.src != WILDCARD:
            continue
        if link.dst == identity or link.dst not in peers:
            continue
        explicit_dsts.add(link.dst)
        out.append((link, peers[link.dst]))
    if spec.default is not None:
        for dst in sorted(peers):
            if dst == identity or dst in explicit_dsts:
                continue
            out.append((Link(identity, dst, spec.default), peers[dst]))
    if _FIRST_BAND - 1 + len(out) > _MAX_BANDS:
        raise WanError(
            f"{identity} carries {len(out)} shaped links but the prio "
            f"qdisc caps at {_MAX_BANDS} bands "
            f"({_MAX_BANDS - _FIRST_BAND + 1} links per host's egress)")
    return [(link, ip, _FIRST_BAND + i)
            for i, (link, ip) in enumerate(out)]


def tc_teardown_command(dev: str = "eth0") -> str:
    return f"sudo tc qdisc del dev {dev} root 2>/dev/null || true"


def tc_setup_commands(spec: WanSpec, identity: str, peers: dict,
                      dev: str = "eth0") -> list:
    """Shell commands installing this host's egress shaping: a prio root
    with one extra band per shaped link, a netem qdisc on each band, and
    a dst-ip u32 filter steering that peer's traffic into it."""
    links = host_links(spec, identity, peers)
    if not links:
        return []
    bands = _FIRST_BAND - 1 + len(links)
    # priomap keeps default traffic in the standard 3 bands; only the
    # u32 filters steer packets into the netem bands.
    cmds = [
        tc_teardown_command(dev),
        f"sudo tc qdisc add dev {dev} root handle 1: prio bands {bands} "
        f"priomap 1 2 2 2 1 2 0 0 1 1 1 1 1 1 1 1",
    ]
    for link, ip, band in links:
        args = netem_args(link.shape)
        # tc parses classid minors and handle majors as HEX: band 10
        # written "1:10" would mean minor 0x10 = 16, a class the prio
        # root never created.  Format every band reference in hex.
        cmds.append(
            f"sudo tc qdisc add dev {dev} parent 1:{band:x} "
            f"handle {band:x}0: netem {args}".rstrip())
        cmds.append(
            f"sudo tc filter add dev {dev} protocol ip parent 1:0 prio 1 "
            f"u32 match ip dst {ip}/32 flowid 1:{band:x}")
    return cmds


def _tc_change(link, band, dev, args: str) -> str:
    cmd = (f"sudo tc qdisc change dev {dev} parent 1:{band:x} "
           f"handle {band:x}0: netem {args}")
    return cmd.rstrip()


def tc_partition_commands(spec: WanSpec, link_name: str, identity: str,
                          peers: dict, dev: str = "eth0") -> list:
    """Mid-run ``link:<name> partition``: 100% loss on the link's band
    for hosts whose egress carries it (empty list for the rest)."""
    return [_tc_change(link, band, dev, "loss 100%")
            for link, _ip, band in host_links(spec, identity, peers)
            if link.label() == link_name]


def tc_heal_commands(spec: WanSpec, link_name: str, identity: str,
                     peers: dict, dev: str = "eth0") -> list:
    """Mid-run ``link:<name> heal``: restore the spec's shape."""
    return [_tc_change(link, band, dev, netem_args(link.shape) or "delay 0ms")
            for link, _ip, band in host_links(spec, identity, peers)
            if link.label() == link_name]


# ---------------------------------------------------------------------------
# WanProxy (the root-free local/CI executor)
# ---------------------------------------------------------------------------

_CHUNK = 65536
_POLL_S = 0.25


class _TokenBucket:
    """Byte-rate limiter for one pump direction of a WanProxy link.

    The old model charged every chunk ``len * 8 / rate`` of sleep
    regardless of how much wall clock had already passed between chunks
    — a sender with natural gaps was double-charged (its idle time
    earned no credit), which made shaped caps increasingly inaccurate
    as the cap dropped (ROADMAP item-5 follow-up: coarse below
    ~1 Mbit).  A token bucket fixes both ends: tokens accrue with
    elapsed time at the link rate (idle time earns credit up to
    ``burst``), each chunk spends its byte count, and only a deficit
    sleeps — so the long-run rate equals the cap for any send pattern.

    ``delay(n)`` returns the seconds the pump must sleep BEFORE
    forwarding the chunk; tokens may go negative (the debt is the sleep
    being returned), and the next refill credits that slept time back.
    Thread-safe: every connection of the direction shares one bucket —
    the link's rate is a property of the link, not of a socket pair.
    The clock is injectable for deterministic tests."""

    def __init__(self, rate_mbit: float, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = 0.0
        self._last = None
        self.set_rate(rate_mbit)

    def set_rate(self, rate_mbit: float):
        with self._lock:
            self._rate = rate_mbit * 1e6 / 8.0  # bytes per second
            # Burst: enough for a short scheduling hiccup, never so much
            # that a low cap stops binding (50 ms of line rate, floored
            # at 8 KiB so tiny caps still make progress chunk by chunk).
            self._burst = max(self._rate * 0.05, 8192.0)
            self._tokens = min(self._tokens, self._burst)

    def delay(self, nbytes: int) -> float:
        """Seconds to sleep before forwarding an nbytes chunk."""
        with self._lock:
            if self._rate <= 0:
                return 0.0
            now = self._clock()
            if self._last is not None:
                self._tokens = min(self._burst,
                                   self._tokens + (now - self._last)
                                   * self._rate)
            else:
                self._tokens = self._burst  # first chunk rides the burst
            self._last = now
            self._tokens -= nbytes
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self._rate


class WanProxy:
    """Userspace delay/loss/rate TCP proxy for ONE directed link.

    Listens on ``127.0.0.1:<listen_port>`` (0 = ephemeral) and forwards
    to ``target``; each forwarded chunk pays the link's latency (+-
    jitter), the rate cap, and the loss lottery (a lost chunk drops the
    whole connection — see the module docstring for why).  The shape
    applies to BOTH pump directions: a TCP conversation over a shaped
    link pays the delay each way, like netem on both hosts' egress.

    ``partition()`` makes the link black-hole (live connections die, new
    ones are accepted and immediately dropped — exactly what a routing
    partition looks like to a dialing peer); ``heal()`` restores the
    spec shape.  ``rng`` is injectable so loss is deterministic in
    tests.

    Rate caps are enforced by a per-direction shared token bucket
    (``_TokenBucket``): elapsed time earns byte credit at the link
    rate, each forwarded chunk spends its size, and only a deficit
    sleeps — accurate at ANY cap (the old per-chunk charge ignored
    inter-chunk idle time, so caps under ~1 Mbit over-shaped).

    ``start()`` returns before the proxy accepts connections: the accept
    loop first waits for the upstream target to answer a dial (so a peer
    probing a shaped front sees the NODE's readiness, not the proxy's).
    Callers that know the target is already up — tests, the bench probe
    — use ``wait_ready()`` to block until the listener is live.
    """

    def __init__(self, target, shape: LinkShape | None = None,
                 listen_port: int = 0, rng=None,
                 connect_timeout: float = 5.0):
        self.target = target
        self.shape = shape or LinkShape()
        self.shape.validate("WanProxy")
        self._listen_port = listen_port
        self._rng = rng or random.Random()
        self._connect_timeout = connect_timeout
        self._partitioned = False
        self._ready = threading.Event()
        self._stopping = threading.Event()
        self._lock = threading.Lock()
        self._listener = None
        self._threads = []
        self._conns = []
        self.port = None
        # One bucket per pump direction, shared across connections: the
        # cap is the LINK's rate each way, like netem on a host's egress.
        self._bucket_fwd = _TokenBucket(self.shape.rate_mbit)
        self._bucket_rev = _TokenBucket(self.shape.rate_mbit)

    # -- control ------------------------------------------------------------

    def start(self) -> int:
        assert self._listener is None, "proxy already started"
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self._listen_port))
        # listen() happens in the accept thread AFTER the upstream
        # answers a dial: until then a connect to the proxy is REFUSED,
        # so a client probing a shaped front sees the NODE's readiness,
        # not the proxy's (otherwise the proxy would defeat the boot
        # wait loop that retries fronts until reachable).
        listener.settimeout(_POLL_S)
        self._listener = listener
        self.port = listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"wanproxy-{self.port}")
        t.start()
        self._threads.append(t)
        return self.port

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Block until the readiness gate has passed and the listener
        accepts connections (i.e. the upstream target answered a dial).
        Returns False on timeout or if the proxy was stopped first."""
        return self._ready.wait(timeout) and not self._stopping.is_set()

    def stop(self):
        self._stopping.set()
        self._ready.set()  # wake wait_ready() callers (they return False)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._drop_all()
        for t in self._threads:
            t.join(timeout=5.0)

    def set_shape(self, shape: LinkShape):
        shape.validate("WanProxy")
        with self._lock:
            self.shape = shape
        self._bucket_fwd.set_rate(shape.rate_mbit)
        self._bucket_rev.set_rate(shape.rate_mbit)

    def partition(self):
        """Black-hole the link: kill live connections, drop new ones."""
        with self._lock:
            self._partitioned = True
        self._drop_all()

    def heal(self):
        with self._lock:
            self._partitioned = False

    def partitioned(self) -> bool:
        with self._lock:
            return self._partitioned

    # -- internals ----------------------------------------------------------

    def _drop_all(self):
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self):
        listener = self._listener
        # Readiness gate: refuse connects until the upstream dials.
        while not self._stopping.is_set():
            try:
                socket.create_connection(self.target,
                                         timeout=_POLL_S).close()
                break
            except OSError:
                time.sleep(_POLL_S)
        if self._stopping.is_set():
            return
        try:
            listener.listen(64)
        except OSError:
            return  # stopped between the gate and the listen
        self._ready.set()
        while not self._stopping.is_set():
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if self.partitioned():
                # The dialer sees an immediate RST/EOF — a black-holed
                # route, not a listening service.
                conn.close()
                continue
            try:
                upstream = socket.create_connection(
                    self.target, timeout=self._connect_timeout)
            except OSError:
                conn.close()
                continue
            conn.settimeout(_POLL_S)
            upstream.settimeout(_POLL_S)
            with self._lock:
                self._conns += [conn, upstream]
                # Prune finished pump threads: a lossy or partitioned
                # link churns connections, and an append-only list
                # would retain every dead thread until stop().
                self._threads = [t for t in self._threads
                                 if t.is_alive()]
            for a, b, bucket in ((conn, upstream, self._bucket_fwd),
                                 (upstream, conn, self._bucket_rev)):
                t = threading.Thread(target=self._pump, args=(a, b, bucket),
                                     daemon=True)
                t.start()
                with self._lock:
                    self._threads.append(t)

    def _pump(self, src_conn, dst_conn, bucket: "_TokenBucket"):
        try:
            # Both ends were bounded at accept time; re-assert here so
            # the bound is visible in the scope doing the recv (the
            # graftlint unbounded-socket-op rule is lexical, and so are
            # reviewers).  Guarded: partition()/stop() may close the
            # socket before this thread's first statement runs.
            try:
                src_conn.settimeout(_POLL_S)
            except OSError:
                return
            while not self._stopping.is_set():
                if self.partitioned():
                    break
                try:
                    data = src_conn.recv(_CHUNK)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break
                with self._lock:
                    shape = self.shape
                if shape.loss_pct and \
                        self._rng.random() * 100.0 < shape.loss_pct:
                    break  # lost chunk = dropped connection (see above)
                delay = 0.0
                if shape.latency_ms:
                    jitter = (self._rng.uniform(-shape.jitter_ms,
                                                shape.jitter_ms)
                              if shape.jitter_ms else 0.0)
                    delay += max(0.0, shape.latency_ms + jitter) / 1e3
                if shape.rate_mbit:
                    # Token bucket, not per-chunk charging: idle time
                    # between chunks earns credit, so the cap is what
                    # the spec says at any rate (see _TokenBucket).
                    delay += bucket.delay(len(data))
                if delay:
                    time.sleep(delay)
                try:
                    dst_conn.sendall(data)
                except OSError:
                    break
        finally:
            for s in (src_conn, dst_conn):
                try:
                    s.close()
                except OSError:
                    pass

"""Fault-plan model: a validated, time-ordered list of fault events.

A plan is declarative — *what* happens *when* — and carries no process
knowledge; the runner hands each due event to an injector (the harness's
``LocalFaultInjector``, or a stub in tests/bench probes).

Targets
    ``sidecar``      the verify sidecar process
    ``sidecar:<i>``  sidecar i of a graftfleet (``--sidecar-fleet k``)
                     run — same actions as ``sidecar``; index 0 is the
                     primary every node dials first.  A plan must pick
                     ONE naming: mixing bare ``sidecar`` with indexed
                     ``sidecar:<i>`` events is rejected (index 0 and the
                     bare name are the same process, which the
                     per-target state machine cannot merge).
    ``node:<i>``     replica i of the local committee (boot order index)
    ``link:<name>``  a directed WAN link by its graftwan spec label
                     (chaos/netem.py) — requires a WAN spec on the run
    ``client:<i>``   the load generator aimed at replica i (graftsurge)

Actions (per target)
    node:     ``kill`` (SIGKILL), ``restart`` (reboot on the same store),
              ``pause`` / ``resume`` (SIGSTOP/SIGCONT — a cheap
              network-partition proxy: the process holds its sockets but
              answers nothing, exactly what a partitioned peer looks
              like to the committee)
    sidecar:  ``kill``, ``restart``, ``degrade`` — the protocol v3
              ``OP_CHAOS`` hook (bounded reply delay, connection drops,
              forced queue-full sheds) for testing client-side handling
              without process murder (``degrade`` params ride in the
              event's ``params`` dict, see sidecar/service.ChaosState) —
              and ``wedge`` (graftguard): the next ``n`` device launches
              hang past their guard deadline, driving the in-sidecar
              supervisor ladder (host-fallback replies, quarantine,
              crash-only reboot, canary) end to end.  DSL:
              ``"5 sidecar wedge"`` or ``"5 sidecar wedge n=2"``.
    link:     ``partition`` (the link black-holes: netem ``loss 100%``
              remotely, a dropped WanProxy locally) and ``heal``
              (restore the spec shape) — the netem partition-heal fault
              class, measured like every other event.
    client:   ``surge`` — a flash crowd (graftsurge): the offered load
              aimed at that replica multiplies by ``x`` for ``for``
              seconds, then returns to baseline.  DSL sugar:
              ``"10 client:0 surge x5 for 20"`` (also accepted as
              ``x=5 for=20``).  Injectors realize it as an extra
              load-generator process at ``(x-1)``× the client's rate,
              killed when the window closes.
    leader-cascade: ``kill`` with ``k=<n>`` (graftview) — the view-change
              storm drill: SIGKILL the leader of each of the next ``k``
              rounds (round-robin election over the sorted committee, the
              C++ LeaderElector's rule), so the committee must survive k
              chained view changes — timeout broadcast, batched TC
              assembly, backoff pacemaker — before a live leader
              proposes again.  DSL: ``"10 leader-cascade kill k=3"``.
              Judged by the ``view-change`` SLO class and the parser's
              TC-formed / round-jump notes.  Which node indices die is a
              runtime decision (it depends on the round the committee
              has reached), so the per-target state machine does not
              track them: mixing leader-cascade with ANY node:<i>
              event in one plan is rejected.

Validation is a per-target state machine over the time-ordered events:
``restart`` must follow ``kill``, ``resume`` must follow ``pause``,
``heal`` must follow ``partition``, ``degrade`` needs a live sidecar,
and surges on one client must not overlap — a plan that cannot
physically execute fails at parse time, not five seconds into a
thirty-second bench.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

ACTIONS = ("kill", "restart", "pause", "resume", "degrade",
           "partition", "heal", "surge", "wedge")
SIDECAR = "sidecar"
LEADER_CASCADE = "leader-cascade"

_NODE_RE = re.compile(r"^node:(\d+)$")
_LINK_RE = re.compile(r"^link:(\S+)$")
_CLIENT_RE = re.compile(r"^client:(\d+)$")
_SIDECAR_IX_RE = re.compile(r"^sidecar:(\d+)$")


def node_index(target: str):
    """``"node:<i>"`` -> i, else None (the one place the target grammar
    is parsed; the injector and plan validation both route through it)."""
    m = _NODE_RE.match(target)
    return int(m.group(1)) if m else None


def link_name(target: str):
    """``"link:<name>"`` -> the graftwan link label, else None."""
    m = _LINK_RE.match(target)
    return m.group(1) if m else None


def client_index(target: str):
    """``"client:<i>"`` -> i, else None (graftsurge load targets)."""
    m = _CLIENT_RE.match(target)
    return int(m.group(1)) if m else None


def sidecar_index(target: str):
    """``"sidecar:<i>"`` -> i (graftfleet indexed sidecar), else None.
    The bare ``"sidecar"`` target is NOT an index — callers route it
    via the SIDECAR constant (it aliases fleet index 0 at injection
    time, and plans may not mix the two namings)."""
    m = _SIDECAR_IX_RE.match(target)
    return int(m.group(1)) if m else None


# Surge parameter defaults — ONE definition shared by validation, the
# injectors, the window math (max_time), the SLO judge, and the parser's
# goodput notes, so a plan omitting a param means the same thing at
# every layer.
SURGE_DEFAULT_X = 2.0
SURGE_DEFAULT_FOR_S = 10.0


def surge_window_s(params) -> float:
    """The surge's active-window length in seconds (default applied)."""
    try:
        return float((params or {}).get("for", SURGE_DEFAULT_FOR_S))
    except (TypeError, ValueError):
        return SURGE_DEFAULT_FOR_S


# graftview: default cascade depth — ONE definition shared by validation,
# the injector, the harness pre-flight quorum check, and the parser's
# client-death tolerance, so an omitted ``k`` means the same thing at
# every layer.
CASCADE_DEFAULT_K = 1


def cascade_k(params) -> int:
    """A leader-cascade event's kill depth (default applied)."""
    try:
        return int((params or {}).get("k", CASCADE_DEFAULT_K))
    except (TypeError, ValueError):
        return CASCADE_DEFAULT_K

# Actions each target kind accepts (sidecar pause would stop the shared
# verify engine for EVERY replica at once — use degrade for that class
# of fault instead, it is observable and bounded).
_NODE_ACTIONS = {"kill", "restart", "pause", "resume"}
_SIDECAR_ACTIONS = {"kill", "restart", "degrade", "wedge"}
_LINK_ACTIONS = {"partition", "heal"}
_CLIENT_ACTIONS = {"surge"}
_CASCADE_ACTIONS = {"kill"}

# degrade params the sidecar's ChaosState accepts (mirrored there; the
# plan validates early so a typo fails at parse time).
DEGRADE_KEYS = ("delay_ms", "shed", "drop", "clear")


class PlanError(ValueError):
    """Malformed or physically unexecutable fault plan."""


@dataclass(frozen=True)
class FaultEvent:
    t: float                    # seconds from the start of the run window
    target: str                 # "sidecar" or "node:<i>"
    action: str
    params: dict = field(default_factory=dict)

    def label(self) -> str:
        return f"t={self.t:g}s {self.action} {self.target}"

    def to_json(self) -> dict:
        out = {"t": self.t, "target": self.target, "action": self.action}
        if self.params:
            out["params"] = dict(self.params)
        return out


@dataclass(frozen=True)
class FaultPlan:
    events: tuple

    def to_json(self) -> list:
        return [e.to_json() for e in self.events]

    def node_indices(self) -> set:
        out = set()
        for e in self.events:
            i = node_index(e.target)
            if i is not None:
                out.add(i)
        return out

    def sidecar_indices(self) -> set:
        """Every graftfleet sidecar index the plan faults (validated
        against the run's fleet size by the harness before boot)."""
        out = set()
        for e in self.events:
            i = sidecar_index(e.target)
            if i is not None:
                out.add(i)
        return out

    def link_names(self) -> set:
        """Every graftwan link the plan faults (validated against the
        run's WAN spec by the harness before anything boots)."""
        out = set()
        for e in self.events:
            name = link_name(e.target)
            if name is not None:
                out.add(name)
        return out

    def max_time(self) -> float:
        """Latest event activity: a surge occupies ``[t, t + for]``, so
        its END is what run-window headroom must clear."""
        out = 0.0
        for e in self.events:
            end = e.t
            if e.action == "surge":
                end += surge_window_s(e.params)
            out = max(out, end)
        return out


def _event_from_dict(obj: dict) -> FaultEvent:
    unknown = set(obj) - {"t", "target", "action", "params"}
    if unknown:
        raise PlanError(f"unknown event key(s) {sorted(unknown)}")
    try:
        t = float(obj["t"])
        target = str(obj["target"])
        action = str(obj["action"])
    except (KeyError, TypeError, ValueError) as e:
        raise PlanError(f"event needs numeric 't', 'target', 'action': {e}")
    params = obj.get("params", {})
    if not isinstance(params, dict):
        raise PlanError(f"{action} {target}: params must be an object")
    return FaultEvent(t, target, action, dict(params))


def _event_from_text(entry: str) -> FaultEvent:
    """``"<t> <target> <action> [k=v ...]"`` -> event (the inline DSL).

    Surge sugar: ``"10 client:0 surge x5 for 20"`` — an ``xN`` token is
    the multiplier, ``for N`` the window seconds (both also accepted in
    k=v form)."""
    toks = entry.split()
    if len(toks) < 3:
        raise PlanError(
            f"bad plan entry {entry!r}: want '<t> <target> <action>'")
    t_raw = toks[0][:-1] if toks[0].endswith("s") else toks[0]
    try:
        t = float(t_raw)
    except ValueError:
        raise PlanError(f"bad event time {toks[0]!r} in {entry!r}")
    params = {}
    rest = list(toks[3:])
    i = 0
    while i < len(rest):
        tok = rest[i]
        if toks[2] == "surge" and re.fullmatch(r"x\d+(\.\d+)?", tok):
            params["x"] = float(tok[1:])
            i += 1
            continue
        if toks[2] == "surge" and tok == "for" and i + 1 < len(rest):
            try:
                params["for"] = float(rest[i + 1])
            except ValueError:
                raise PlanError(
                    f"bad surge duration {rest[i + 1]!r} in {entry!r}")
            i += 2
            continue
        if "=" not in tok:
            raise PlanError(f"bad param {tok!r} in {entry!r} (want k=v)")
        k, v = tok.split("=", 1)
        try:
            params[k] = int(v)
        except ValueError:
            try:
                params[k] = float(v)
            except ValueError:
                params[k] = v
        i += 1
    return FaultEvent(t, toks[1], toks[2], params)


def _validate(events) -> FaultPlan:
    # Per-target liveness state machine over the time-ordered sequence.
    state: dict[str, str] = {}
    surge_until: dict[str, float] = {}
    ordered = sorted(events, key=lambda e: e.t)
    for e in ordered:
        if not (e.t >= 0.0 and e.t == e.t and e.t != float("inf")):
            raise PlanError(f"{e.label()}: event time must be finite >= 0")
        if e.action not in ACTIONS:
            raise PlanError(f"{e.label()}: unknown action (have "
                            f"{', '.join(ACTIONS)})")
        if e.target == SIDECAR or _SIDECAR_IX_RE.match(e.target):
            allowed = _SIDECAR_ACTIONS
        elif e.target == LEADER_CASCADE:
            allowed = _CASCADE_ACTIONS
        elif _NODE_RE.match(e.target):
            allowed = _NODE_ACTIONS
        elif _LINK_RE.match(e.target):
            allowed = _LINK_ACTIONS
        elif _CLIENT_RE.match(e.target):
            allowed = _CLIENT_ACTIONS
        else:
            raise PlanError(f"{e.label()}: target must be 'sidecar', "
                            "'sidecar:<i>', 'leader-cascade', 'node:<i>', "
                            "'link:<name>', or 'client:<i>'")
        if e.action not in allowed:
            raise PlanError(f"{e.label()}: {e.target} does not support "
                            f"{e.action} (allowed: {', '.join(sorted(allowed))})")
        if e.params and e.action not in ("degrade", "surge", "wedge") \
                and e.target != LEADER_CASCADE:
            raise PlanError(f"{e.label()}: only degrade, surge, wedge, "
                            "and leader-cascade take params")
        if e.target == LEADER_CASCADE:
            bad = set(e.params) - {"k"}
            if bad:
                raise PlanError(f"{e.label()}: unknown leader-cascade "
                                f"param(s) {sorted(bad)} (have k)")
            k = e.params.get("k", CASCADE_DEFAULT_K)
            if not isinstance(k, int) or isinstance(k, bool) or k < 1:
                raise PlanError(
                    f"{e.label()}: leader-cascade k must be an int >= 1 "
                    f"(got {k!r})")
        if e.action == "wedge":
            bad = set(e.params) - {"n"}
            if bad:
                raise PlanError(f"{e.label()}: unknown wedge param(s) "
                                f"{sorted(bad)} (have n)")
            n = e.params.get("n", 1)
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                raise PlanError(
                    f"{e.label()}: wedge n must be an int >= 1 "
                    f"(got {n!r})")
        if e.action == "surge":
            bad = set(e.params) - {"x", "for"}
            if bad:
                raise PlanError(f"{e.label()}: unknown surge param(s) "
                                f"{sorted(bad)} (have x, for)")
            x = e.params.get("x", SURGE_DEFAULT_X)
            dur = e.params.get("for", SURGE_DEFAULT_FOR_S)
            for key, v, lo in (("x", x, 1.0), ("for", dur, 0.0)):
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or v != v or v == float("inf") or not v > lo:
                    raise PlanError(
                        f"{e.label()}: surge {key} must be a finite "
                        f"number > {lo:g} (got {v!r})")
            if e.t < surge_until.get(e.target, -1.0):
                raise PlanError(
                    f"{e.label()}: overlaps the previous surge on "
                    f"{e.target} (still running until "
                    f"t={surge_until[e.target]:g}s)")
            surge_until[e.target] = e.t + float(dur)
        if e.action == "degrade":
            bad = set(e.params) - set(DEGRADE_KEYS)
            if bad:
                raise PlanError(f"{e.label()}: unknown degrade param(s) "
                                f"{sorted(bad)} (have "
                                f"{', '.join(DEGRADE_KEYS)})")
            # Mirror ChaosState.configure's value rules so a typo'd value
            # fails here, not as a mid-run injection failure that costs
            # the whole bench window.
            for key in ("delay_ms", "shed", "drop"):
                v = e.params.get(key)
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool) or v < 0):
                    raise PlanError(
                        f"{e.label()}: degrade {key} must be an int >= 0 "
                        f"(got {v!r})")
        if e.target == LEADER_CASCADE:
            # Which node indices die is a runtime decision, so the
            # per-target state machine cannot track a cascade; keep it
            # stateless (two cascades in one plan are legal).
            continue
        cur = state.get(e.target, "up")
        if e.action == "kill" and cur == "down":
            raise PlanError(f"{e.label()}: target is already down")
        if e.action == "restart" and cur != "down":
            raise PlanError(f"{e.label()}: restart must follow a kill")
        if e.action == "pause" and cur != "up":
            raise PlanError(f"{e.label()}: pause needs a live target")
        if e.action == "resume" and cur != "paused":
            raise PlanError(f"{e.label()}: resume must follow a pause")
        if e.action in ("degrade", "wedge") and cur != "up":
            raise PlanError(
                f"{e.label()}: {e.action} needs a live sidecar")
        if e.action == "partition" and cur != "up":
            raise PlanError(f"{e.label()}: link is already partitioned")
        if e.action == "heal" and cur != "partitioned":
            raise PlanError(f"{e.label()}: heal must follow a partition")
        state[e.target] = {"kill": "down", "restart": "up",
                           "pause": "paused", "resume": "up",
                           "degrade": "up", "partition": "partitioned",
                           "heal": "up", "surge": "up",
                           "wedge": "up"}[e.action]
    # A cascade kills nodes the state machine cannot name, so ANY
    # explicit node:<i> event in the same plan could operate on a
    # replica the cascade already murdered — a later restart/resume
    # would fail at runtime, and a paused replica reads as live to the
    # cascade (poll() is None under SIGSTOP) so even pause/resume pairs
    # can have their second half invalidated.  Unexecutable: reject.
    if any(e.target == LEADER_CASCADE for e in ordered) and \
            any(node_index(e.target) is not None for e in ordered):
        raise PlanError(
            "a plan mixing leader-cascade with node:<i> events cannot "
            "be validated (the cascade's victims are chosen at "
            "runtime); use separate plans")
    # Bare "sidecar" and indexed "sidecar:0" name the SAME process, but
    # the state machine above tracked them as independent targets — a
    # mixed plan could validate and then double-kill at runtime.
    if any(e.target == SIDECAR for e in ordered) and \
            any(sidecar_index(e.target) is not None for e in ordered):
        raise PlanError(
            "a plan mixing the bare 'sidecar' target with indexed "
            "'sidecar:<i>' targets cannot be validated (the bare name "
            "aliases fleet index 0); pick one naming")
    return FaultPlan(tuple(ordered))


def parse_plan(spec) -> FaultPlan:
    """Parse + validate a fault plan from any accepted shape:

    * a ``FaultPlan`` (returned as-is),
    * a list of event dicts (or of DSL strings),
    * a path to a JSON file (a list, or ``{"events": [...]}``),
    * an inline DSL string: ``";"``/newline-separated
      ``"<t> <target> <action> [k=v ...]"`` entries, e.g.
      ``"5 sidecar kill; 10 sidecar restart; 12 node:1 pause; 15 node:1 resume"``.

    Raises :class:`PlanError` on anything malformed or unexecutable.
    """
    if isinstance(spec, FaultPlan):
        return spec
    if isinstance(spec, str):
        if os.path.isfile(spec):
            try:
                with open(spec, encoding="utf-8") as f:
                    obj = json.load(f)
            except (OSError, ValueError) as e:
                raise PlanError(f"cannot read fault plan {spec!r}: {e}")
            if isinstance(obj, dict):
                obj = obj.get("events")
            if not isinstance(obj, list):
                raise PlanError(f"{spec!r}: want a JSON list of events "
                                "(or {'events': [...]})")
            spec = obj
        else:
            spec = [entry for entry in
                    re.split(r"[;\n]", spec) if entry.strip()]
            if not spec:
                raise PlanError("empty fault plan")
    if not isinstance(spec, (list, tuple)):
        raise PlanError(f"unsupported fault-plan spec type "
                        f"{type(spec).__name__}")
    events = []
    for entry in spec:
        if isinstance(entry, FaultEvent):
            events.append(entry)
        elif isinstance(entry, dict):
            events.append(_event_from_dict(entry))
        elif isinstance(entry, str):
            events.append(_event_from_text(entry.strip()))
        else:
            raise PlanError(f"bad plan entry {entry!r}")
    return _validate(events)

"""graftchaos: scripted fault injection for the local testbed.

The paper's claim — device-accelerated QC verification inside a live
HotStuff deployment — only matters if consensus stays live when the
accelerator path misbehaves.  The reference benchmarks model crash
faults as replicas that were never booted (benchmark/local.py:75-76);
Twins-style BFT testing (Bano et al.) shows that *scripted, mid-run*
fault schedules are what actually shake out recovery bugs.  This
package is the declarative half of that testing story:

  plan.py      fault-plan model + parser (JSON file, dict list, or a
               one-line DSL: ``"5 sidecar kill; 10 sidecar restart"``)
  runner.py    executes a plan against a running bench on its own
               thread, recording wall-clock timestamps per event
  recovery.py  per-fault recovery latency from the executed events and
               the committee's commit timeline (shared by the harness
               LogParser and bench.py's ``chaos`` headline field)
  netem.py     graftwan link shaping: per-host-pair WAN specs compiled
               to ``tc netem`` for fleets, with a root-free userspace
               TCP proxy (``WanProxy``) so local/CI runs exercise the
               identical plan schema
  slo.py       per-fault-class recovery SLOs: pass/fail verdicts over
               the recovery summary (shared by LogParser notes, the
               strict testbed assertion, and the bench headline)

The harness side (process murder, SIGSTOP partitions, sidecar chaos
RPCs, remote ssh injection) lives in ``hotstuff_tpu/harness/faults.py``;
the sidecar's in-process fault hook (``OP_CHAOS``) in
``sidecar/service.py``.
"""

from .netem import LinkShape, WanError, WanProxy, WanSpec, \
    parse_wan  # noqa: F401
from .plan import ACTIONS, LEADER_CASCADE, FaultEvent, FaultPlan, \
    PlanError, cascade_k, client_index, link_name, node_index, \
    parse_plan  # noqa: F401
from .recovery import summarize_recovery  # noqa: F401
from .runner import PlanRunner  # noqa: F401
from .slo import DEFAULT_SLO_MS, SloError, fault_class, judge, \
    judge_baseline_recovery, parse_slos, throughput_series  # noqa: F401

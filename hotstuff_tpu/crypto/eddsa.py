"""Ed25519 batch verification API: host-side preparation + TPU execution.

This is the framework's equivalent of the reference's signature API surface
(crypto/src/lib.rs:177-224): ``verify`` / ``verify_batch`` — except batch
verification returns a *per-signature validity mask* computed on device,
which is what quorum-certificate verification wants
(consensus/src/messages.rs:180-198 rejects a QC when any vote fails).

Host responsibilities (cheap, byte-oriented): SHA-512 challenge hashing,
encoding canonicality checks (y < p, S < L), limb/bit unpacking into dense
arrays.  Device responsibilities (the FLOPs): point decompression, the
fixed-base comb + windowed variable-base ladder (ops/ed25519.py), batched
across the whole quorum.

Batch shapes are padded to power-of-two buckets so XLA compiles a handful of
program shapes, then results are sliced back.
"""

from __future__ import annotations

import hashlib

import jax.numpy as jnp
import numpy as np

from ..ops import ed25519 as E
from ..utils.intmath import next_pow2  # noqa: F401  (re-export: THE
# bucketing rule — sharded_verify and the sidecar import it from here)

P = E.P
L = E.L

_MIN_BUCKET = 8


def _bucket(n: int) -> int:
    return next_pow2(n, _MIN_BUCKET)


_L_BYTES = np.frombuffer(L.to_bytes(32, "little"), np.uint8).astype(np.int16)


def _ge_p(y_bytes: np.ndarray) -> np.ndarray:
    """(B, 32) u8 little-endian values with bit 255 cleared: rows >= p."""
    return ((y_bytes[:, 31] == 0x7F)
            & (y_bytes[:, 1:31] == 0xFF).all(axis=1)
            & (y_bytes[:, 0] >= 0xED))


def _lt_L(s_bytes: np.ndarray) -> np.ndarray:
    """(B, 32) u8 little-endian scalars: rows < L (vectorized lex compare)."""
    diff = s_bytes[:, ::-1].astype(np.int16) - _L_BYTES[::-1]
    nonzero = diff != 0
    first = np.argmax(nonzero, axis=1)
    lead = diff[np.arange(len(diff)), first]
    return nonzero.any(axis=1) & (lead < 0)


# The eight small-order (8-torsion) points have five distinct y values, and
# the set is closed under negation — so comparing the sign-cleared y against
# this table is an exact small-order test for canonically-encoded points
# (non-canonical y >= p is rejected separately by _ge_p).  dalek's
# verify_strict rejects small-order A and R (crypto/src/lib.rs:204-208);
# without the check, pk = identity encoding plus sig = ([S]B || S) verifies
# ANY message, a universal forgery that breaks vote attribution.
_SMALL_ORDER_Y = np.frombuffer(b"".join(
    y.to_bytes(32, "little")
    for y in (
        0,       # order-4 pair (x = +-sqrt(-1))
        1,       # identity
        P - 1,   # (0, -1), order 2
        # order-8 pairs: y8 and p - y8
        0x7A03AC9277FDC74EC6CC392CFA53202A0F67100D760B3CBA4FD84D3D706A17C7,
        0x05FC536D880238B13933C6D305ACDFD5F098EFF289F4C345B027B2C28F95E826,
    )), np.uint8).reshape(5, 32)


def _small_order(y_bytes: np.ndarray) -> np.ndarray:
    """(B, 32) u8 sign-cleared y encodings: rows that are 8-torsion."""
    return (y_bytes[:, None, :] == _SMALL_ORDER_Y[None]).all(-1).any(-1)


def prepare_batch(msgs, pks, sigs):
    """Lists of (msg bytes, pk 32B, sig 64B) -> dict of device-ready arrays.

    Returns compact uint8 arrays — a (B,32), r (B,32), s (B,32), k (B,32) —
    plus the host_ok canonicality mask. 130 B/signature is all that crosses
    the host->device boundary; limb/bit expansion happens on device
    (ops/ed25519.verify_compact), which matters on tunneled TPUs where the
    transfer, not the ladder, bounds throughput. The per-signature SHA-512
    challenge hash is the only non-vectorized host work.
    """
    n = len(msgs)
    assert len(pks) == n and len(sigs) == n
    if all(len(pk) == 32 for pk in pks) and all(len(s) == 64 for s in sigs):
        # Common case: two bulk copies instead of 2n per-row frombuffers
        # (the per-row path costs ~2 us/sig of pure python overhead).
        pk_arr = np.frombuffer(b"".join(pks), np.uint8).reshape(n, 32).copy()
        sig_arr = np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 64).copy()
        len_ok = np.ones((n,), bool)
    else:
        pk_arr = np.zeros((n, 32), np.uint8)
        sig_arr = np.zeros((n, 64), np.uint8)
        len_ok = np.zeros((n,), bool)
        for i, (pk, sig) in enumerate(zip(pks, sigs)):
            if len(pk) == 32 and len(sig) == 64:
                pk_arr[i] = np.frombuffer(pk, np.uint8)
                sig_arr[i] = np.frombuffer(sig, np.uint8)
                len_ok[i] = True

    ay_b = pk_arr.copy()
    ay_b[:, 31] &= 0x7F
    ry_b = sig_arr[:, :32].copy()
    ry_b[:, 31] &= 0x7F
    s_bytes = np.ascontiguousarray(sig_arr[:, 32:])
    host_ok = (len_ok & ~_ge_p(ay_b) & ~_ge_p(ry_b) & _lt_L(s_bytes)
               & ~_small_order(ay_b) & ~_small_order(ry_b))

    # challenge scalars k = SHA512(R||A||M) mod L (host hashing, C-speed).
    # One contiguous bytearray + a single frombuffer at the end: per-row
    # numpy assignments dominated this loop before (~2 us/sig of pure
    # overhead at N=1024).
    k_buf = bytearray(32 * n)
    sig_rows, pk_rows = sig_arr.tobytes(), pk_arr.tobytes()
    for i in np.nonzero(host_ok)[0]:
        h = hashlib.sha512(sig_rows[64 * i:64 * i + 32]
                           + pk_rows[32 * i:32 * i + 32] + msgs[i]).digest()
        k = int.from_bytes(h, "little") % L
        k_buf[32 * i:32 * i + 32] = k.to_bytes(32, "little")
    k_bytes = np.frombuffer(bytes(k_buf), np.uint8).reshape(n, 32)

    # One allocation; a/r/s/k are views into it (the sharded path slices,
    # the single-device path ships the whole row).
    packed = np.concatenate(
        [pk_arr, sig_arr[:, :32], s_bytes, k_bytes], axis=1)
    return dict(a=packed[:, 0:32], r=packed[:, 32:64], s=packed[:, 64:96],
                k=packed[:, 96:128], packed=packed, host_ok=host_ok)


def split_packed_rows(packed: np.ndarray, host_ok=None) -> dict:
    """(n, 128) already-prepared rows -> the prepare_batch dict shape,
    without re-deriving anything.  The RLC bisection paths slice prepared
    rows by index and re-enter the batch verifiers with them; rows
    selected through a host_ok mask are canonical by construction, so the
    default mask is all-True."""
    n = packed.shape[0]
    if host_ok is None:
        host_ok = np.ones((n,), bool)
    return dict(a=packed[:, 0:32], r=packed[:, 32:64], s=packed[:, 64:96],
                k=packed[:, 96:128], packed=packed, host_ok=host_ok)


# Per-program sub-batch cap. A/B-measured best end-to-end shape on v5e
# (scripts/eval_device.py): larger batches run as sub-batches of this size
# scanned inside ONE dispatch (ops/ed25519.verify_packed_chunked), which
# amortizes the fixed per-dispatch tunnel cost while keeping every conv's
# group count at a size XLA handles well.
MAX_SUBBATCH = 1024


def verify_batch(msgs, pks, sigs, *, pad: bool = True) -> np.ndarray:
    """Batch Ed25519 verify on the default JAX device -> (N,) bool mask.

    TPU analogue of ``Signature::verify_batch``
    (reference: crypto/src/lib.rs:210-223), with per-signature results.
    Any batch size works: n <= 1024 pads to a power-of-two bucket and runs
    one plain program; larger n runs as ceil(n/1024) sub-batches inside a
    single chunked-scan dispatch.
    """
    return verify_batch_submit(msgs, pks, sigs, pad=pad)()


def verify_batch_submit(msgs, pks, sigs, *, pad: bool = True):
    """Dispatch a batch verify WITHOUT fetching the result.

    Returns a zero-argument ``fetch`` callable producing the (N,) bool
    mask.  Dispatch is asynchronous on the device, so the caller can
    submit the next batch (or do host work) while this one executes —
    on a tunneled TPU the fixed per-dispatch cost (~15-20 ms) otherwise
    serializes every launch behind the previous launch's result fetch,
    halving the sidecar engine's verify throughput.
    """
    return verify_batch_pack(msgs, pks, sigs, pad=pad)()


def verify_batch_pack(msgs, pks, sigs, *, pad: bool = True):
    """Pack stage of a batch verify: ALL host-side work — byte decode,
    canonicality checks, SHA-512 challenges, bucket padding and the
    h2d transfer — happens here, on the caller's thread.  The returned
    ``dispatch()`` fires the donated device program (cheap — the input
    already lives on device) and returns ``fetch() -> (N,) bool mask``.

    This is the three-stage split the sidecar engine's double-buffered
    pipeline needs: its pack thread stages launch N+1 (this function)
    while launch N executes, and the engine thread only ever pays the
    dispatch + fetch cost.  ``verify_batch_submit`` is the two-stage
    wrapper (pack + dispatch in one call) for callers without a pack
    thread.
    """
    n = len(msgs)
    if n == 0:
        return lambda: (lambda: np.zeros((0,), bool))
    prep = prepare_batch(msgs, pks, sigs)
    host_ok = prep["host_ok"]
    dispatch_rows = _pack_rows(prep["packed"], n, pad)

    def dispatch():
        fetch_rows = dispatch_rows()
        return lambda: fetch_rows() & host_ok

    return dispatch


def _pack_rows(packed: np.ndarray, n: int, pad: bool):
    """(n, 128) prepared rows -> staged device input; returns
    dispatch() -> fetch() -> (n,) bool mask.  Single home of the
    bucket/pad/chunk policy shared by the eager, submit and pack paths.
    The h2d transfer happens HERE (pack stage); the donated program
    launch happens inside dispatch()."""
    # The launches below DONATE their input buffer; forcing host-side
    # rows here guarantees each jnp.asarray is a fresh device copy, so a
    # caller's (possibly device-resident) array is never invalidated.
    packed = np.asarray(packed)
    if n <= MAX_SUBBATCH:
        m = _bucket(n) if pad else n
        if m != n:
            packed = np.pad(packed, [(0, m - n), (0, 0)])
        dev_in = jnp.asarray(packed)

        def dispatch():
            dev = E.verify_packed_donated(dev_in)
            return lambda: np.asarray(dev)[:n]

        return dispatch
    g = -(-n // MAX_SUBBATCH)
    if pad:  # bound the number of compiled scan lengths: next power of two
        g = next_pow2(g)
    m = g * MAX_SUBBATCH
    if m != n:
        packed = np.pad(packed, [(0, m - n), (0, 0)])
    dev_in = jnp.asarray(packed.reshape(g, MAX_SUBBATCH, 128))

    def dispatch():
        dev = E.verify_packed_chunked_donated(dev_in)
        return lambda: np.asarray(dev).reshape(m)[:n]

    return dispatch


def _dispatch_rows(packed: np.ndarray, n: int, pad: bool):
    """Two-stage form of :func:`_pack_rows` (pack + dispatch in one
    call); returns fetch() -> (n,) bool mask."""
    return _pack_rows(packed, n, pad)()


def verify_prepared_rows(packed: np.ndarray, n: int, *,
                         pad: bool = True) -> np.ndarray:
    """(n, 128) prepared rows -> (n,) device mask (no host_ok fold)."""
    return _dispatch_rows(packed, n, pad)()


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Single-signature verify routed through the device path."""
    return bool(verify_batch([msg], [pk], [sig])[0])


# ---------------------------------------------------------------------------
# Random-linear-combination batch verification (one MSM per quorum)
# ---------------------------------------------------------------------------

# Below this the per-signature program is cheaper than the MSM's fixed
# Horner/comb tail; it is also the bisection floor — sub-batches this
# small resolve per signature, which is what pinpoints a bad vote.
RLC_MIN_MSM = 4

_RLC_DOMAIN = b"hotstuff-tpu/rlc-batch-v1"


def _rlc_coeffs(rows: np.ndarray, salt: bytes) -> np.ndarray:
    """(n, 128) prepared rows -> (n, 32) uint8 coefficient rows: 128-bit
    nonzero z_i in canonical little-endian bytes (high 16 bytes zero).

    Deterministic per call: a SHA-512 counter-mode PRF seeded by the
    batch CONTENT (all rows), the bisection path (``salt``) and a domain
    tag.  Soundness needs the z_i to be unpredictable to whoever chose
    the signatures *before* the batch was formed — hashing every row into
    the seed gives the standard derandomized batch-verification argument:
    changing any bit of any signature re-randomizes every coefficient.
    128-bit coefficients put an adversarial cancellation at ~2^-128, the
    scheme's security level; anything shorter would make the combined
    check the weakest link (see ops/ed25519 module notes).
    """
    n = rows.shape[0]
    seed = hashlib.sha512(_RLC_DOMAIN + salt + rows.tobytes()).digest()
    blocks = -(-n // 4)  # 4 x 16-byte coefficients per SHA-512 block
    stream = b"".join(
        hashlib.sha512(seed + i.to_bytes(4, "little")).digest()
        for i in range(blocks))
    z = np.zeros((n, 32), np.uint8)
    z[:, :16] = np.frombuffer(stream, np.uint8)[:16 * n].reshape(n, 16)
    # An all-zero row (p = 2^-128) would EXCLUDE the signature from the
    # combined check; force its low byte to 1 (still deterministic).
    dead = ~z.any(axis=1)
    z[dead, 0] = 1
    return z


def verify_batch_rlc(msgs, pks, sigs, *, pad: bool = True) -> np.ndarray:
    """Batch Ed25519 verify via the random-linear-combination check ->
    (N,) bool mask, bit-identical to :func:`verify_batch`.

    Fast path: ONE device dispatch checks the combined equation
    [sum z_i S_i]B == sum [z_i]R_i + sum [z_i k_i]A_i over the whole
    batch (ops/ed25519.verify_rlc_packed).  All-valid batches — the
    steady state of quorum-certificate verification — pay one MSM
    instead of 2n scalar ladders.  When the combined check fails, the
    batch bisects (fresh coefficients per sub-batch) down to
    RLC_MIN_MSM, below which the per-signature path pinpoints each bad
    vote — so the returned mask always matches verify_batch exactly,
    valid or not; an adversary can make us pay the old per-signature
    price, never accept a bad vote (up to the 2^-128 RLC bound).

    Batches beyond MAX_SUBBATCH fall back to the per-signature chunked
    path (the MSM's conv group count scales with batch, and quorums that
    size should shard across the mesh instead —
    parallel/sharded_verify.verify_rlc_sharded).
    """
    return verify_batch_rlc_submit(msgs, pks, sigs, pad=pad)()


def verify_batch_rlc_submit(msgs, pks, sigs, *, pad: bool = True,
                            on_bisect=None):
    """Dispatch the combined RLC check WITHOUT fetching its verdict.

    Returns a zero-argument ``fetch`` producing the (N,) bool mask
    (bit-identical to :func:`verify_batch`), so the sidecar engine can
    pipeline the next launch behind this one exactly like
    :func:`verify_batch_submit`.  The all-valid steady state stays fully
    asynchronous (one dispatched MSM, verdict read at fetch); only a
    failed combined check falls back to synchronous bisection inside
    ``fetch`` — the adversarial slow path, which already pays
    per-signature prices.  ``on_bisect`` (if given) is invoked once when
    that happens — how the scheduler's telemetry counts ``rlc_bisect``
    launches without the crypto layer importing it.

    Host-canonicality failures and degenerate sizes (fewer than
    RLC_MIN_MSM canonical rows, or more than MAX_SUBBATCH) dispatch the
    per-signature program instead — same contract, same mask.
    """
    return verify_batch_rlc_pack(msgs, pks, sigs, pad=pad,
                                 on_bisect=on_bisect)()


def verify_batch_rlc_pack(msgs, pks, sigs, *, pad: bool = True,
                          on_bisect=None):
    """Pack stage of the combined RLC check: host preparation, the
    coefficient PRF, bucket padding and the h2d transfers happen here;
    the returned ``dispatch()`` fires the donated one-MSM program and
    returns the ``fetch`` described on :func:`verify_batch_rlc_submit`
    (which is this function's two-stage wrapper)."""
    n = len(msgs)
    if n == 0:
        return lambda: (lambda: np.zeros((0,), bool))
    prep = prepare_batch(msgs, pks, sigs)
    packed = prep["packed"]
    idx = np.nonzero(prep["host_ok"])[0]
    m = len(idx)
    if m < RLC_MIN_MSM or m > MAX_SUBBATCH:
        rows = np.ascontiguousarray(packed[idx])
        dispatch_rows = _pack_rows(rows, m, pad) if m else None

        def dispatch_degenerate():
            fetch_rows = dispatch_rows() if dispatch_rows else None

            def fetch_degenerate():
                mask = np.zeros(n, bool)
                if fetch_rows is not None:
                    mask[idx] = fetch_rows()
                return mask

            return fetch_degenerate

        return dispatch_degenerate
    rows = np.ascontiguousarray(packed[idx])
    bucket = _bucket(m) if pad else m
    z = np.zeros((bucket, 32), np.uint8)
    z[:m] = _rlc_coeffs(rows, b"")
    if bucket != m:
        rows = np.pad(rows, [(0, bucket - m), (0, 0)])
    # Fresh host arrays -> fresh device buffers; the launch donates arg 0
    # (same discipline as _pack_rows).
    dev_rows, dev_z = jnp.asarray(rows), jnp.asarray(z)

    def dispatch():
        dev = E.verify_rlc_packed_donated(dev_rows, dev_z)

        def fetch():
            mask = np.zeros(n, bool)
            if bool(np.asarray(dev)):
                mask[idx] = True
                return mask
            if on_bisect is not None:
                on_bisect()
            mid = m // 2
            _rlc_resolve(packed, idx[:mid], mask, b"L", pad)
            _rlc_resolve(packed, idx[mid:], mask, b"R", pad)
            return mask

        return fetch

    return dispatch


def _rlc_resolve(packed: np.ndarray, indices: np.ndarray,
                 out: np.ndarray, salt: bytes, pad: bool) -> None:
    """Resolve ``out[indices]`` for host-canonical rows: combined RLC
    check first, bisection on failure, per-signature floor."""
    n = len(indices)
    if n == 0:
        return
    if n < RLC_MIN_MSM or n > MAX_SUBBATCH:
        rows = np.ascontiguousarray(packed[indices])
        out[indices] = verify_prepared_rows(rows, n, pad=pad)
        return
    rows = np.ascontiguousarray(packed[indices])
    m = _bucket(n) if pad else n
    z = np.zeros((m, 32), np.uint8)
    z[:n] = _rlc_coeffs(rows, salt)
    if m != n:
        rows = np.pad(rows, [(0, m - n), (0, 0)])
    # Fresh host arrays -> fresh device buffers; the launch donates arg 0
    # (same discipline as _dispatch_rows).
    ok = bool(np.asarray(E.verify_rlc_packed_donated(
        jnp.asarray(rows), jnp.asarray(z))))
    if ok:
        out[indices] = True
        return
    mid = n // 2
    _rlc_resolve(packed, indices[:mid], out, salt + b"L", pad)
    _rlc_resolve(packed, indices[mid:], out, salt + b"R", pad)

"""Pure-python Ed25519 (RFC 8032) — host reference implementation.

Roles (mirroring the reference repo's split):
* signing + keygen for the node/sidecar (the reference signs on the CPU via
  ed25519-dalek, crypto/src/lib.rs:177-202; signing is cheap and stays on
  host in the TPU build too),
* ground truth for the device verifier's tests, replacing the role of the
  reference's off-chain python implementations
  (off-chain-benchmarking/eddsa.py).

Not constant-time; verification-side use only handles public data, and the
signing path is a benchmarking/testing facility like the reference's.
"""

from __future__ import annotations

import hashlib

from ..utils.intmath import BX, BY, D, L, P, recover_x

B = (BX, BY, 1, BX * BY % P)
IDENT = (0, 1, 1, 0)


def pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_dbl(p):
    return pt_add(p, p)


def scalar_mult(s: int, p):
    q = IDENT
    while s > 0:
        if s & 1:
            q = pt_add(q, p)
        p = pt_dbl(p)
        s >>= 1
    return q


def pt_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def encode_point(p) -> bytes:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = x * zi % P, y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def decode_point(s: bytes):
    val = int.from_bytes(s, "little")
    y = val & ((1 << 255) - 1)
    sign = val >> 255
    x = recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _h(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little")


def _clamp(a: int) -> int:
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_key(seed: bytes) -> bytes:
    a = _clamp(int.from_bytes(hashlib.sha512(seed).digest()[:32], "little"))
    return encode_point(scalar_mult(a, B))


def generate_keypair(seed: bytes) -> tuple[bytes, bytes]:
    """seed (32 bytes) -> (seed, public_key).  Analogue of the reference's
    generate_keypair (crypto/src/lib.rs:169-175)."""
    assert len(seed) == 32
    return seed, public_key(seed)


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(int.from_bytes(h[:32], "little"))
    prefix = h[32:]
    pk = encode_point(scalar_mult(a, B))
    r = _h(prefix + msg) % L
    r_enc = encode_point(scalar_mult(r, B))
    k = _h(r_enc + pk + msg) % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def is_small_order(pt) -> bool:
    """True for the 8-torsion points ([8]P == identity)."""
    return pt_equal(scalar_mult(8, pt), IDENT)


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Host reference verifier: [S]B == R + [k]A (cofactorless, strict).

    Strictness matches dalek's ``verify_strict`` (the reference's
    single-signature path, crypto/src/lib.rs:204-208): small-order A or R
    is rejected — with A small-order, ``sig = R||S`` where R = [S]B - [k]A
    verifies ANY message (for A = identity, any R = [S]B works), so
    accepting such keys breaks vote attribution in the committee.
    """
    if len(sig) != 64 or len(pk) != 32:
        return False
    a_pt = decode_point(pk)
    r_pt = decode_point(sig[:32])
    s = int.from_bytes(sig[32:], "little")
    if a_pt is None or r_pt is None or s >= L:
        return False
    if is_small_order(a_pt) or is_small_order(r_pt):
        return False
    k = _h(sig[:32] + pk + msg) % L
    return pt_equal(scalar_mult(s, B), pt_add(r_pt, scalar_mult(k, a_pt)))

"""graftingress signed-transaction codec — Python twin of the pinned C++
frame header (native/src/mempool/tx_frame.hpp).

Frame layout (version 2, all integers big-endian)::

    [0]        version      = TX_FRAME_VERSION (2)
    [1:33]     user pubkey  (Ed25519; derived from seed + user index)
    [33:41]    nonce        (u64; client-local monotonic counter)
    [41:45]    payload_len  (u32; must equal len(frame) - TX_FRAME_OVERHEAD)
    [45:45+n]  payload      (legacy inner tx: marker u8 + id u64 BE +
                             padding; marker 0=sample, 1=filler,
                             2=forged-marker)
    [-64:]     signature    (Ed25519 over the signing preimage)

Signing preimage: ``SHA-512(TX_SIGN_DOMAIN + frame[:-64])[:32]`` — the
32-byte digest is the Ed25519 message, the same (digest, pk, sig) record
shape every verify path in this repo ships to the sidecar bulk lane.

Per-user keys are derived deterministically so a verifier can recompute
any user's pubkey without key distribution::

    seed32 = SHA-512(TX_KEY_DOMAIN + seed u64 BE + user u64 BE)[:32]

graftlint's wire cross-checker (analysis/wirecheck.py, rule
``txframe-mismatch``) asserts the constants below match the C++ header —
edit BOTH sides or the gate fails.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import NamedTuple

from . import ref_ed25519

TX_FRAME_VERSION = 2
TX_PK_LEN = 32
TX_NONCE_LEN = 8
TX_LEN_LEN = 4
TX_SIG_LEN = 64
TX_FRAME_HEADER_LEN = 45   # version + pubkey + nonce + payload_len
TX_FRAME_OVERHEAD = 109    # header + signature
TX_MIN_PAYLOAD = 9         # marker + u64 id
TX_MAX_PAYLOAD = 1048576   # 1 MiB
TX_MARKER_SAMPLE = 0
TX_MARKER_FILLER = 1
TX_MARKER_FORGED = 2

TX_SIGN_DOMAIN = b"graftingress-tx-v1"
TX_KEY_DOMAIN = b"graftingress-key-v1"
# Sidecar context tag for admission-verify batches: exactly CTX_LEN(32)
# bytes and deliberately NON-zero (protocol.py decodes an all-zero ctx
# as "no tag", which would hide ingress-fed bulk records from OP_STATS).
INGRESS_CTX = b"graftingress-tx-admission-ctx-v1"
assert len(INGRESS_CTX) == 32 and any(INGRESS_CTX)

assert TX_FRAME_HEADER_LEN == 1 + TX_PK_LEN + TX_NONCE_LEN + TX_LEN_LEN
assert TX_FRAME_OVERHEAD == TX_FRAME_HEADER_LEN + TX_SIG_LEN


class TxFrameError(ValueError):
    """Structurally invalid signed-tx frame; .reason mirrors the C++
    TxParse enum (``not-signed`` / ``truncated`` / ``bad-payload-len``)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


class SignedTx(NamedTuple):
    pk: bytes
    nonce: int
    payload: bytes
    sig: bytes


def derive_user_seed(seed: int, user: int) -> bytes:
    """32-byte Ed25519 key seed for (bench seed, user index)."""
    pre = (TX_KEY_DOMAIN + int(seed).to_bytes(8, "big")
           + int(user).to_bytes(8, "big"))
    return hashlib.sha512(pre).digest()[:32]


def derive_user_keypair(seed: int, user: int) -> tuple[bytes, bytes]:
    """(signing seed, public key) for one simulated user."""
    return ref_ed25519.generate_keypair(derive_user_seed(seed, user))


class UserKeyring:
    """Bounded LRU of expanded per-user keypairs (derive on first
    arrival): a 1e6-user sweep only ever holds ``capacity`` expanded
    keys, mirroring the C++ TxKeyring."""

    def __init__(self, seed: int, capacity: int = 4096):
        self.seed = seed
        self.capacity = max(1, int(capacity))
        self.derivations = 0
        self._lru: OrderedDict[int, tuple[bytes, bytes]] = OrderedDict()

    def get(self, user: int) -> tuple[bytes, bytes]:
        kp = self._lru.get(user)
        if kp is not None:
            self._lru.move_to_end(user)
            return kp
        if len(self._lru) >= self.capacity:
            self._lru.popitem(last=False)
        kp = derive_user_keypair(self.seed, user)
        self._lru[user] = kp
        self.derivations += 1
        return kp

    def __len__(self) -> int:
        return len(self._lru)


def build_payload(marker: int, tx_id: int, size: int = TX_MIN_PAYLOAD) -> bytes:
    """Legacy inner tx payload: marker + u64 id + zero padding."""
    size = max(int(size), TX_MIN_PAYLOAD)
    body = bytes([marker]) + int(tx_id).to_bytes(8, "big")
    return body + b"\x00" * (size - len(body))


def preimage_digest(frame_without_sig: bytes) -> bytes:
    """32-byte Ed25519 message for a frame's signing preimage."""
    return hashlib.sha512(TX_SIGN_DOMAIN + frame_without_sig).digest()[:32]


def build_signed_tx(keypair: tuple[bytes, bytes], nonce: int, payload: bytes,
                    flip_sig_bit: bool = False) -> bytes:
    """One signed frame; ``flip_sig_bit`` forges the signature while
    keeping the structure valid (the seeded forgery mix)."""
    seed, pk = keypair
    head = (bytes([TX_FRAME_VERSION]) + pk
            + int(nonce).to_bytes(8, "big")
            + len(payload).to_bytes(4, "big") + payload)
    sig = ref_ed25519.sign(seed, preimage_digest(head))
    if flip_sig_bit:
        sig = bytes([sig[0] ^ 0x01]) + sig[1:]
    return head + sig


def parse_signed_tx(frame: bytes) -> SignedTx:
    """Structural parse; raises TxFrameError on malformed frames (the
    decode-level fuzz contract: error out, never mis-slice)."""
    if not frame or frame[0] != TX_FRAME_VERSION:
        raise TxFrameError("not-signed", f"first byte {frame[:1]!r}")
    if len(frame) < TX_FRAME_OVERHEAD + TX_MIN_PAYLOAD:
        raise TxFrameError("truncated", f"{len(frame)} B")
    plen = int.from_bytes(frame[41:45], "big")
    if plen < TX_MIN_PAYLOAD or plen > TX_MAX_PAYLOAD:
        raise TxFrameError("bad-payload-len", f"declared {plen}")
    if plen + TX_FRAME_OVERHEAD != len(frame):
        raise TxFrameError(
            "bad-payload-len",
            f"declared {plen} vs frame {len(frame)} B")
    return SignedTx(
        pk=frame[1:33],
        nonce=int.from_bytes(frame[33:41], "big"),
        payload=frame[45:45 + plen],
        sig=frame[45 + plen:],
    )


def admission_record(frame: bytes) -> tuple[bytes, bytes, bytes]:
    """(digest, pk, sig) verify record for one structurally valid frame
    — the exact triple the admission stage ships to OP_VERIFY_BULK."""
    tx = parse_signed_tx(frame)
    return preimage_digest(frame[:-TX_SIG_LEN]), tx.pk, tx.sig


def verify_tx(frame: bytes) -> bool:
    """Host ground-truth verify of one frame (test fixtures; slow)."""
    try:
        digest, pk, sig = admission_record(frame)
    except TxFrameError:
        return False
    return ref_ed25519.verify(pk, digest, sig)

"""Off-chain signature microbenchmarks.

Reproduces the reference's two workloads with this framework's schemes:
  * single-verify latency, N iterations per scheme
    (off-chain-benchmarking/main.py:10-38, 100 iters)
  * batch/aggregate-verify scaling sweep over batch sizes
    (off-chain-benchmarking/main.py:78-111: 20..300 step 20;
     production/src/main.rs:19-64: EdDSA sequential vs BLS aggregate)
plus the TPU batch path that is this framework's reason to exist.

Results go to stdout as JSON lines and optionally to CSV/plots (pandas +
matplotlib, as the reference used).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _timed(fn, iters=1):
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    dt = (time.perf_counter() - t0) / iters
    return out, dt


def _make_ed25519(n, msg_len=32):
    from . import eddsa

    rng = np.random.default_rng(11)
    msgs, pks, sigs = [], [], []
    for _ in range(n):
        sk, pk = eddsa.key_gen(rng.bytes(32))
        msg = rng.bytes(msg_len)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(eddsa.sign(sk, msg))
    return msgs, pks, sigs


def measure_single(iters=100, schemes=("eddsa", "ecdsa", "schnorr", "bls")):
    """Single sign + verify latency per scheme (reference main.py:10-38)."""
    results = []
    msg = b"off-chain benchmark message"

    if "eddsa" in schemes:
        from . import eddsa

        sk, pk = eddsa.key_gen(b"\x01" * 32)
        sig, sign_dt = _timed(lambda: eddsa.sign(sk, msg), iters)
        ok, verify_dt = _timed(lambda: eddsa.verify(pk, msg, sig), iters)
        assert ok
        results.append(("eddsa", sign_dt, verify_dt))

    if "ecdsa" in schemes:
        from . import ecdsa

        sk, pk = ecdsa.key_gen(b"\x02")
        sig, sign_dt = _timed(lambda: ecdsa.sign(sk, msg), iters)
        ok, verify_dt = _timed(lambda: ecdsa.verify(pk, msg, sig), iters)
        assert ok
        results.append(("ecdsa", sign_dt, verify_dt))

    if "schnorr" in schemes:
        from . import schnorr

        sk, pk = schnorr.key_gen(b"\x03")
        sig, sign_dt = _timed(lambda: schnorr.sign(sk, msg), iters)
        ok, verify_dt = _timed(lambda: schnorr.verify(pk, msg, sig), iters)
        assert ok
        results.append(("schnorr", sign_dt, verify_dt))

    if "bls" in schemes:
        from . import bls12381 as bls

        # Pure-Python pairing: a handful of iterations is plenty.
        bls_iters = max(1, min(iters, 3))
        sk, pk = bls.key_gen(b"\x04")
        sig, sign_dt = _timed(lambda: bls.sign(sk, msg), bls_iters)
        ok, verify_dt = _timed(lambda: bls.verify(pk, msg, sig), bls_iters)
        assert ok
        results.append(("bls", sign_dt, verify_dt))

    rows = []
    for scheme, sign_dt, verify_dt in results:
        row = {
            "workload": "single",
            "scheme": scheme,
            "sign_ms": round(sign_dt * 1e3, 4),
            "verify_ms": round(verify_dt * 1e3, 4),
        }
        rows.append(row)
        print(json.dumps(row))
    return rows


def measure_batch(sizes=tuple(range(20, 301, 20)), tpu=True, tpu_bls=True):
    """Batch-verify scaling (reference main.py:78-111 sweep + the Rust
    production comparison): Ed25519 sequential host loop vs TPU batch vs
    BLS aggregate (common message, 2-pairing fast path), host vs device."""
    from . import bls12381 as bls
    from . import eddsa

    rows = []
    msgs_all, pks_all, sigs_all = _make_ed25519(max(sizes))

    # BLS: one shared message, aggregated signature (QC-style).
    bls_keys = [bls.key_gen(i.to_bytes(2, "big") * 4)
                for i in range(max(sizes))]
    common = b"common quorum digest"
    bls_sigs = [bls.sign(sk, common) for sk, _ in bls_keys]

    if tpu_bls:
        from ..ops import bls381 as dbls

        dbls.selfcheck()
        # One warm-up compiles the pairing program; its device shape is
        # N-independent (pk aggregation happens on host), so every sweep
        # size reuses it.
        agg0 = bls.aggregate(bls_sigs[:2])
        assert dbls.verify_aggregate_common(
            [pk for _, pk in bls_keys[:2]], common, agg0)

    for n in sizes:
        msgs, pks, sigs = msgs_all[:n], pks_all[:n], sigs_all[:n]
        _, host_dt = _timed(lambda: eddsa.verify_batch_host(msgs, pks, sigs))
        row = {
            "workload": "batch",
            "n": n,
            "eddsa_host_ms": round(host_dt * 1e3, 3),
        }

        if tpu:
            # Warm the jit cache for this bucket shape, then time.
            eddsa.verify_batch_tpu(msgs, pks, sigs)
            mask, tpu_dt = _timed(
                lambda: eddsa.verify_batch_tpu(msgs, pks, sigs))
            assert all(mask)
            row["eddsa_tpu_ms"] = round(tpu_dt * 1e3, 3)

        agg = bls.aggregate(bls_sigs[:n])
        apks = [pk for _, pk in bls_keys[:n]]
        ok, bls_dt = _timed(
            lambda: bls.verify_aggregate_common(apks, common, agg))
        assert ok
        row["bls_aggregate_ms"] = round(bls_dt * 1e3, 3)

        if tpu_bls:
            ok, dbls_dt = _timed(
                lambda: dbls.verify_aggregate_common(apks, common, agg))
            assert ok
            row["bls_aggregate_tpu_ms"] = round(dbls_dt * 1e3, 3)

        rows.append(row)
        print(json.dumps(row))
    return rows


def measure_message_length(lengths=tuple(range(64, 6401, 640)), iters=20):
    """Single-verify cost vs message length
    (production/src/main.rs:67-108)."""
    from . import eddsa

    rows = []
    rng = np.random.default_rng(5)
    sk, pk = eddsa.key_gen(b"\x09" * 32)
    for length in lengths:
        msg = rng.bytes(length)
        sig = eddsa.sign(sk, msg)
        ok, dt = _timed(lambda: eddsa.verify(pk, msg, sig), iters)
        assert ok
        row = {
            "workload": "msg-length",
            "scheme": "eddsa",
            "msg_len": length,
            "verify_ms": round(dt * 1e3, 4),
        }
        rows.append(row)
        print(json.dumps(row))
    return rows


def to_csv(rows, path):
    import pandas as pd

    pd.DataFrame(rows).to_csv(path, index=False)


def plot_batch(rows, path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    batch = [r for r in rows if r.get("workload") == "batch"]
    if not batch:
        return
    n = [r["n"] for r in batch]
    plt.figure(figsize=(6.4, 4.8))
    for key, label in (("eddsa_host_ms", "Ed25519 host loop"),
                       ("eddsa_tpu_ms", "Ed25519 TPU batch"),
                       ("bls_aggregate_ms", "BLS aggregate (common msg)")):
        ys = [r[key] for r in batch if key in r]
        if len(ys) == len(n):
            plt.plot(n, ys, marker="o", label=label)
    plt.xlabel("signatures")
    plt.ylabel("verify time (ms)")
    plt.yscale("log")
    plt.grid(True, alpha=0.3)
    plt.legend()
    plt.savefig(path, bbox_inches="tight")

"""secp256k1 curve arithmetic + ECDSA + Schnorr (host reference).

The reference's off-chain suite benchmarks "EdDSA"/Schnorr/ECDSA over
petlib's EcGroup(714) = secp256k1 (off-chain-benchmarking/eddsa.py:7,
schnorr.py, ecdsa.py). petlib is not in this image, so this module is the
self-contained arithmetic those schemes run on: Jacobian point ops over the
256-bit prime field, ECDSA with RFC 6979-style deterministic nonces, and a
hash-challenge Schnorr matching the reference's scheme shape
(off-chain-benchmarking/schnorr.py: R = kG, e = H(R||P||m), s = k + e*d).
"""

from __future__ import annotations

import hashlib
import hmac

# Curve: y^2 = x^3 + 7 over F_p
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
B = 7

# Affine points are (x, y) tuples; None is the identity.


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def point_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def point_mul(k: int, p=None):
    """k*P via Jacobian double-and-add (affine in/out)."""
    if p is None:
        p = (GX, GY)
    k %= N
    if k == 0:
        return None
    # Jacobian coordinates: (X, Y, Z), x = X/Z^2, y = Y/Z^3
    def jdbl(q):
        X, Y, Z = q
        if Y == 0:
            return (0, 1, 0)
        S = 4 * X * Y * Y % P
        M = 3 * X * X % P
        X2 = (M * M - 2 * S) % P
        Y2 = (M * (S - X2) - 8 * Y * Y * Y * Y) % P
        Z2 = 2 * Y * Z % P
        return (X2, Y2, Z2)

    def jadd(q, a):  # q jacobian, a affine
        X1, Y1, Z1 = q
        if Z1 == 0:
            return (a[0], a[1], 1)
        x2, y2 = a
        Z1Z1 = Z1 * Z1 % P
        U2 = x2 * Z1Z1 % P
        S2 = y2 * Z1Z1 * Z1 % P
        if U2 == X1:
            if S2 != Y1:
                return (0, 1, 0)
            return jdbl(q)
        H = (U2 - X1) % P
        HH = H * H % P
        I = 4 * HH % P
        J = H * I % P
        r = 2 * (S2 - Y1) % P
        V = X1 * I % P
        X3 = (r * r - J - 2 * V) % P
        Y3 = (r * (V - X3) - 2 * Y1 * J) % P
        Z3 = 2 * Z1 * H % P
        return (X3, Y3, Z3)

    acc = (0, 1, 0)
    for bit in bin(k)[2:]:
        acc = jdbl(acc)
        if bit == "1":
            acc = jadd(acc, p)
    X, Y, Z = acc
    if Z == 0:
        return None
    zinv = _inv(Z, P)
    z2 = zinv * zinv % P
    return (X * z2 % P, Y * z2 * zinv % P)


def on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - B) % P == 0


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------

def point_encode(p) -> bytes:
    """SEC1 compressed (33 bytes)."""
    if p is None:
        return b"\x00"
    x, y = p
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def point_decode(data: bytes):
    if data == b"\x00":
        return None
    if len(data) != 33 or data[0] not in (2, 3):
        raise ValueError("bad point encoding")
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("not on curve")
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


# ---------------------------------------------------------------------------
# key generation
# ---------------------------------------------------------------------------

def key_gen(seed: bytes | None = None):
    """-> (sk int, pk point). Deterministic from seed when given."""
    if seed is None:
        import secrets

        d = secrets.randbelow(N - 1) + 1
    else:
        d = int.from_bytes(hashlib.sha512(seed).digest(), "big") % (N - 1) + 1
    return d, point_mul(d)


def _hash_int(*parts: bytes) -> int:
    h = hashlib.sha256()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "big")


# ---------------------------------------------------------------------------
# ECDSA (off-chain-benchmarking/ecdsa.py capability)
# ---------------------------------------------------------------------------

def _rfc6979_k(d: int, h1: bytes) -> int:
    """Deterministic nonce (RFC 6979, SHA-256)."""
    V = b"\x01" * 32
    K = b"\x00" * 32
    x = d.to_bytes(32, "big")
    K = hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 1 <= k < N:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def ecdsa_sign(d: int, msg: bytes):
    h1 = hashlib.sha256(msg).digest()
    z = int.from_bytes(h1, "big") % N
    while True:
        k = _rfc6979_k(d, h1)
        R = point_mul(k)
        r = R[0] % N
        if r == 0:
            continue
        s = _inv(k, N) * (z + r * d) % N
        if s == 0:
            continue
        if s > N // 2:  # low-s normalization
            s = N - s
        return (r, s)


def ecdsa_verify(pk, msg: bytes, sig) -> bool:
    r, s = sig
    if not (1 <= r < N and 1 <= s < N) or pk is None or not on_curve(pk):
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
    w = _inv(s, N)
    u1, u2 = z * w % N, r * w % N
    pt = point_add(point_mul(u1), point_mul(u2, pk))
    return pt is not None and pt[0] % N == r


def ecdsa_sig_to_der(sig) -> bytes:
    """DER encoding (for cross-checks against OpenSSL)."""
    def int_der(v):
        b = v.to_bytes((v.bit_length() + 8) // 8 or 1, "big")
        return b"\x02" + bytes([len(b)]) + b

    body = int_der(sig[0]) + int_der(sig[1])
    return b"\x30" + bytes([len(body)]) + body


# ---------------------------------------------------------------------------
# Schnorr (off-chain-benchmarking/schnorr.py capability; also what that
# repo's "eddsa.py" actually implements over secp256k1)
# ---------------------------------------------------------------------------

def schnorr_sign(d: int, msg: bytes, nonce_seed: bytes | None = None):
    """R = kG, e = H(R || P || m), s = k + e*d  ->  (R point, s int)."""
    pk = point_mul(d)
    seed = nonce_seed or (d.to_bytes(32, "big") + msg)
    k = int.from_bytes(hashlib.sha512(seed).digest(), "big") % (N - 1) + 1
    R = point_mul(k)
    e = _hash_int(point_encode(R), point_encode(pk), msg) % N
    s = (k + e * d) % N
    return (R, s)


def schnorr_verify(pk, msg: bytes, sig) -> bool:
    R, s = sig
    if R is None or not on_curve(R) or not (0 <= s < N):
        return False
    if pk is None or not on_curve(pk):
        return False
    e = _hash_int(point_encode(R), point_encode(pk), msg) % N
    # sG == R + eP
    return point_mul(s) == point_add(R, point_mul(e, pk))

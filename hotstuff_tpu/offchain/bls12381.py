"""BLS12-381 pairing + BLS signatures (host reference implementation).

Capability mirror of the reference's BLS benchmarking path
(off-chain-benchmarking/bls.py: key_gen/sign/verify/aggregate/
verify_aggregate via bplib, and off-chain-benchmarking/production using
filecoin's bls-signatures). Neither library exists in this image, so this
is a from-scratch pure-Python BLS12-381: Fq/Fq2/Fq12 tower, G1/G2 curves,
optimal-ate pairing (Miller loop in Fq12 with the sextic-twist embedding),
and filecoin's group assignment (public keys in G1, signatures in G2) —
encoded UNCOMPRESSED here (96-byte G1, 192-byte G2; filecoin's compressed
48/96-byte forms would need Fq2 square roots on every decode).  Decoding
enforces on-curve AND prime-order subgroup membership, matching
bls-signatures' deserialize semantics.  Verification batches all Miller
loops into a single final
exponentiation (product-of-pairings), which is also the shape a future
device port wants.

Correctness is locked by algebraic self-tests (tests/test_offchain.py):
bilinearity, non-degeneracy, subgroup orders, and signature roundtrips.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

# Field / curve parameters
Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = 15132376222941642752  # |x|; the BLS parameter is -x

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


# ---------------------------------------------------------------------------
# Fq2 = Fq[u] / (u^2 + 1): elements are (a, b) = a + b u
# ---------------------------------------------------------------------------

def fq2_add(x, y):
    return ((x[0] + y[0]) % Q, (x[1] + y[1]) % Q)


def fq2_sub(x, y):
    return ((x[0] - y[0]) % Q, (x[1] - y[1]) % Q)


def fq2_mul(x, y):
    a = x[0] * y[0] % Q
    b = x[1] * y[1] % Q
    c = (x[0] + x[1]) * (y[0] + y[1]) % Q
    return ((a - b) % Q, (c - a - b) % Q)


def fq2_neg(x):
    return ((-x[0]) % Q, (-x[1]) % Q)


def fq2_inv(x):
    norm = (x[0] * x[0] + x[1] * x[1]) % Q
    ninv = pow(norm, -1, Q)
    return (x[0] * ninv % Q, (-x[1]) * ninv % Q)


FQ2_ONE = (1, 0)
FQ2_ZERO = (0, 0)


# ---------------------------------------------------------------------------
# Fq12 = Fq[w] / (w^12 - 2 w^6 + 2): elements are 12-tuples of Fq coeffs.
# (The py_ecc-style direct degree-12 representation; the sextic twist of
# G2 into this ring is _twist below.)
# ---------------------------------------------------------------------------

FQ12_MOD = (2, 0, 0, 0, 0, 0, -2, 0, 0, 0, 0, 0)  # w^12 = -2 + 2 w^6
FQ12_ONE = (1,) + (0,) * 11
FQ12_ZERO = (0,) * 12


def fq12_add(x, y):
    return tuple((a + b) % Q for a, b in zip(x, y))


def fq12_sub(x, y):
    return tuple((a - b) % Q for a, b in zip(x, y))


def fq12_neg(x):
    return tuple((-a) % Q for a in x)


def fq12_scalar(x, k):
    return tuple(a * k % Q for a in x)


def fq12_mul(x, y):
    prod = [0] * 23
    for i, a in enumerate(x):
        if a == 0:
            continue
        for j, b in enumerate(y):
            if b:
                prod[i + j] += a * b
    # reduce degrees 22..12 with w^12 = 2 w^6 - 2
    for d in range(22, 11, -1):
        c = prod[d]
        if c:
            prod[d] = 0
            prod[d - 6] += 2 * c
            prod[d - 12] -= 2 * c
    return tuple(c % Q for c in prod[:12])


def fq12_inv(x):
    # Extended Euclid over Fq[w] modulo the degree-12 modulus.
    lm, hm = [1] + [0] * 12, [0] * 13
    low = list(x) + [0]
    high = [(-c) % Q for c in FQ12_MOD] + [1]
    # high = modulus polynomial coefficients (monic, degree 12)
    high = [2 % Q, 0, 0, 0, 0, 0, (-2) % Q, 0, 0, 0, 0, 0, 1]

    def deg(p):
        for i in range(len(p) - 1, -1, -1):
            if p[i]:
                return i
        return 0

    def poly_rounded_div(a, b):
        dega, degb = deg(a), deg(b)
        temp = list(a)
        out = [0] * len(a)
        inv_lead = pow(b[degb], -1, Q)
        for i in range(dega - degb, -1, -1):
            out[i] = out[i] + temp[degb + i] * inv_lead
            for c in range(degb + 1):
                temp[c + i] = (temp[c + i] - out[i] * b[c])
        return [c % Q for c in out[:deg(out) + 1]]

    while deg(low):
        r = poly_rounded_div(high, low)
        r += [0] * (13 - len(r))
        nm = list(hm)
        new = list(high)
        for i in range(13):
            for j in range(13 - i):
                nm[i + j] -= lm[i] * r[j]
                new[i + j] -= low[i] * r[j]
        nm = [c % Q for c in nm]
        new = [c % Q for c in new]
        lm, low, hm, high = nm, new, lm, low
    inv_low0 = pow(low[0], -1, Q)
    return tuple(c * inv_low0 % Q for c in lm[:12])


def fq12_pow(x, n):
    result = FQ12_ONE
    base = x
    while n:
        if n & 1:
            result = fq12_mul(result, base)
        base = fq12_mul(base, base)
        n >>= 1
    return result


# ---------------------------------------------------------------------------
# Curves. G1 over Fq: y^2 = x^3 + 4. G2 over Fq2: y^2 = x^3 + 4(u+1).
# Points are (x, y) or None for infinity; generic over the field ops.
# ---------------------------------------------------------------------------

class _Ops:
    """Field operation bundle so one point-arithmetic works over Fq, Fq2
    and Fq12."""

    def __init__(self, add, sub, mul, neg, inv, one, zero, b):
        self.add, self.sub, self.mul, self.neg, self.inv = \
            add, sub, mul, neg, inv
        self.one, self.zero, self.b = one, zero, b

    def scalar(self, x, k):
        if isinstance(x, tuple):
            return tuple(c * k % Q for c in x)
        return x * k % Q


_fq = _Ops(lambda a, b: (a + b) % Q, lambda a, b: (a - b) % Q,
           lambda a, b: a * b % Q, lambda a: (-a) % Q,
           lambda a: pow(a, -1, Q), 1, 0, 4)
_fq2 = _Ops(fq2_add, fq2_sub, fq2_mul, fq2_neg, fq2_inv, FQ2_ONE, FQ2_ZERO,
            fq2_mul((4, 0), (1, 1)))
_fq12 = _Ops(fq12_add, fq12_sub, fq12_mul, fq12_neg, fq12_inv, FQ12_ONE,
             FQ12_ZERO, None)


def _double(pt, ops):
    if pt is None:
        return None
    x, y = pt
    if y == ops.zero:
        return None
    lam = ops.mul(ops.scalar(ops.mul(x, x), 3), ops.inv(ops.scalar(y, 2)))
    nx = ops.sub(ops.mul(lam, lam), ops.scalar(x, 2))
    ny = ops.sub(ops.mul(lam, ops.sub(x, nx)), y)
    return (nx, ny)


def _add(p1, p2, ops):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return _double(p1, ops)
        return None
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    nx = ops.sub(ops.sub(ops.mul(lam, lam), x1), x2)
    ny = ops.sub(ops.mul(lam, ops.sub(x1, nx)), y1)
    return (nx, ny)


def _mul(pt, k, ops):
    result = None
    addend = pt
    while k:
        if k & 1:
            result = _add(result, addend, ops)
        addend = _double(addend, ops)
        k >>= 1
    return result


def g1_generator():
    return (G1_X, G1_Y)


def g2_generator():
    return (G2_X, G2_Y)


def g1_add(p1, p2):
    return _add(p1, p2, _fq)


def g1_mul(pt, k):
    return _jac_mul(pt, k % R, _fq)


def g1_neg(pt):
    return None if pt is None else (pt[0], (-pt[1]) % Q)


def g2_add(p1, p2):
    return _add(p1, p2, _fq2)


def g2_mul(pt, k):
    return _jac_mul(pt, k % R, _fq2)


def g2_neg(pt):
    return None if pt is None else (pt[0], fq2_neg(pt[1]))


def _jac_double(P, ops):
    """Jacobian doubling on y^2 = x^3 + b (a = 0): 2M + 5S, no inversion."""
    X, Y, Z = P
    mul, sub, sc = ops.mul, ops.sub, ops.scalar
    A = mul(X, X)
    B = mul(Y, Y)
    C = mul(B, B)
    D = sc(sub(sub(mul(ops.add(X, B), ops.add(X, B)), A), C), 2)
    E = sc(A, 3)
    X3 = sub(mul(E, E), sc(D, 2))
    Y3 = sub(mul(E, sub(D, X3)), sc(C, 8))
    Z3 = sc(mul(Y, Z), 2)
    return (X3, Y3, Z3)


def _jac_add_affine(P, q, ops):
    """Mixed Jacobian + affine addition; returns None for the identity."""
    X1, Y1, Z1 = P
    x2, y2 = q
    mul, sub = ops.mul, ops.sub
    Z1Z1 = mul(Z1, Z1)
    U2 = mul(x2, Z1Z1)
    S2 = mul(y2, mul(Z1, Z1Z1))
    H = sub(U2, X1)
    r = sub(S2, Y1)
    if H == ops.zero:
        if r == ops.zero:
            return _jac_double(P, ops)
        return None
    HH = mul(H, H)
    HHH = mul(H, HH)
    V = mul(X1, HH)
    X3 = sub(sub(mul(r, r), HHH), ops.scalar(V, 2))
    Y3 = sub(mul(r, sub(V, X3)), mul(Y1, HHH))
    Z3 = mul(Z1, H)
    return (X3, Y3, Z3)


def _jac_mul(pt, k, ops):
    """Affine [k]pt via Jacobian left-to-right double-and-add: one field
    inversion total instead of one per bit — this is what makes the [R]P
    subgroup membership test affordable in pure python."""
    if pt is None or k == 0:
        return None
    acc = None
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _jac_double(acc, ops)
            if acc[2] == ops.zero:
                acc = None
        if bit == "1":
            acc = (pt[0], pt[1], ops.one) if acc is None \
                else _jac_add_affine(acc, pt, ops)
    if acc is None or acc[2] == ops.zero:
        return None
    zinv = ops.inv(acc[2])
    zinv2 = ops.mul(zinv, zinv)
    return (ops.mul(acc[0], zinv2), ops.mul(acc[1], ops.mul(zinv2, zinv)))


def g1_in_subgroup(pt) -> bool:
    """Prime-order subgroup membership ([R]P == identity). The filecoin
    bls-signatures crate the reference benches against enforces this on
    every deserialize (off-chain-benchmarking/production/Cargo.toml:10);
    aggregate verification over cofactor-component points is undefined."""
    return pt is None or (g1_on_curve(pt) and _jac_mul(pt, R, _fq) is None)


def g2_in_subgroup(pt) -> bool:
    return pt is None or (g2_on_curve(pt) and _jac_mul(pt, R, _fq2) is None)


def g1_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + 4)) % Q == 0


def g2_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return fq2_sub(fq2_mul(y, y),
                   fq2_add(fq2_mul(fq2_mul(x, x), x), _fq2.b)) == FQ2_ZERO


# ---------------------------------------------------------------------------
# Pairing
# ---------------------------------------------------------------------------

_W2 = (0, 0) + (1,) + (0,) * 9   # w^2
_W3 = (0, 0, 0) + (1,) + (0,) * 8  # w^3
_W2_INV = fq12_inv(_W2)
_W3_INV = fq12_inv(_W3)


def _twist(pt):
    """Embed a G2 point (over Fq2, basis 1,u) into E(Fq12): coefficients
    re-expressed in the (1, w^6) basis (u = w^6 - 1), then untwisted by
    w^-2 / w^-3 — the G2 curve's b = 4(u+1) equals 4w^6 in this basis, so
    dividing lands exactly on G1's curve y^2 = x^3 + 4 over Fq12."""
    if pt is None:
        return None
    x, y = pt
    nx = tuple(((x[0] - x[1]) % Q if i == 0 else (x[1] if i == 6 else 0))
               for i in range(12))
    ny = tuple(((y[0] - y[1]) % Q if i == 0 else (y[1] if i == 6 else 0))
               for i in range(12))
    return (fq12_mul(nx, _W2_INV), fq12_mul(ny, _W3_INV))


def _cast_g1_fq12(pt):
    if pt is None:
        return None
    x, y = pt
    return ((x,) + (0,) * 11, (y,) + (0,) * 11)


def _linefunc(p1, p2, t):
    """Evaluate the line through p1,p2 at t (all over Fq12)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = fq12_mul(fq12_sub(y2, y1), fq12_inv(fq12_sub(x2, x1)))
        return fq12_sub(fq12_mul(m, fq12_sub(xt, x1)), fq12_sub(yt, y1))
    if y1 == y2:
        m = fq12_mul(fq12_scalar(fq12_mul(x1, x1), 3),
                     fq12_inv(fq12_scalar(y1, 2)))
        return fq12_sub(fq12_mul(m, fq12_sub(xt, x1)), fq12_sub(yt, y1))
    return fq12_sub(xt, x1)


def miller_loop(q_twisted, p_fq12):
    """Miller loop over the BLS parameter (ate pairing, untwisted inputs).

    q_twisted: G2 point already embedded in E(Fq12); p_fq12: G1 point cast
    into Fq12 coordinates. Result needs final_exponentiate."""
    if q_twisted is None or p_fq12 is None:
        return FQ12_ONE
    rpt = q_twisted
    f = FQ12_ONE
    for bit in bin(BLS_X)[3:]:
        f = fq12_mul(fq12_mul(f, f), _linefunc(rpt, rpt, p_fq12))
        rpt = _add(rpt, rpt, _fq12)
        if bit == "1":
            f = fq12_mul(f, _linefunc(rpt, q_twisted, p_fq12))
            rpt = _add(rpt, q_twisted, _fq12)
    # BLS parameter is negative: conjugate/invert
    return fq12_inv(f)


_FINAL_EXP = (Q**12 - 1) // R


def final_exponentiate(f):
    return fq12_pow(f, _FINAL_EXP)


def pairing(p_g1, q_g2):
    """e(P in G1, Q in G2) -> Fq12 element of order dividing r."""
    return final_exponentiate(
        miller_loop(_twist(q_g2), _cast_g1_fq12(p_g1)))


def multi_pairing(pairs):
    """prod e(P_i, Q_i) with ONE final exponentiation — the shape every
    BLS verify below uses (2 pairings -> 1 final exp; n-message aggregate
    -> n+1 Miller loops, 1 final exp)."""
    f = FQ12_ONE
    for p_g1, q_g2 in pairs:
        f = fq12_mul(f, miller_loop(_twist(q_g2), _cast_g1_fq12(p_g1)))
    return final_exponentiate(f)


# ---------------------------------------------------------------------------
# Encoding (uncompressed here; sizes follow the filecoin convention the
# reference's production bench uses: G1 pk, G2 sig)
# ---------------------------------------------------------------------------

def g1_encode(pt) -> bytes:
    if pt is None:
        return b"\x40" + b"\x00" * 95
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def _g1_decode_uncached(data: bytes):
    if data[0] == 0x40:
        return None
    x = int.from_bytes(data[:48], "big")
    y = int.from_bytes(data[48:], "big")
    pt = (x, y)
    if not g1_on_curve(pt):
        raise ValueError("not on G1")
    if _jac_mul(pt, R, _fq) is not None:
        raise ValueError("G1 point not in the prime-order subgroup")
    return pt


def g1_decode(data: bytes):
    """Decode + validate (on-curve AND prime-order subgroup, matching
    filecoin bls-signatures deserialize semantics). Cached: committee
    public keys repeat on every verify, and the [R]P membership test is
    the expensive part of decoding."""
    return _g1_decode_cached(bytes(data))


@functools.lru_cache(maxsize=4096)
def _g1_decode_cached(data: bytes):
    return _g1_decode_uncached(data)


def g2_encode(pt) -> bytes:
    if pt is None:
        return b"\x40" + b"\x00" * 191
    x, y = pt
    return (x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big")
            + y[1].to_bytes(48, "big") + y[0].to_bytes(48, "big"))


def g2_decode_lax(data: bytes):
    """Decode with the on-curve check only (no subgroup test).  For callers
    aggregating many fresh signatures who subgroup-check the single
    aggregate instead: the verified pairing statement depends only on the
    aggregate, so that costs one [R]P ladder instead of N."""
    if data[0] == 0x40:
        return None
    x = (int.from_bytes(data[48:96], "big"),
         int.from_bytes(data[:48], "big"))
    y = (int.from_bytes(data[144:192], "big"),
         int.from_bytes(data[96:144], "big"))
    pt = (x, y)
    if not g2_on_curve(pt):
        raise ValueError("not on G2")
    return pt


def g2_decode(data: bytes):
    pt = g2_decode_lax(data)
    if pt is not None and _jac_mul(pt, R, _fq2) is not None:
        raise ValueError("G2 point not in the prime-order subgroup")
    return pt


# ---------------------------------------------------------------------------
# Hash-to-G2 (try-and-increment; benchmarking-grade, not RFC 9380)
# ---------------------------------------------------------------------------

# G2 lives on a sextic twist E'/Fq2. With base trace t = x + 1 (x the
# negative BLS parameter), the Fq2 trace is t2 = t^2 - 2q, the CM part f2
# satisfies t2^2 - 4q^2 = -3 f2^2, and the sextic twists have orders
# q^2 + 1 - (±3 f2 ± t2)/2. The right twist is the r-divisible one; its
# cofactor clears arbitrary curve points into G2. Computed (not hardcoded)
# so a parameter slip fails loudly at import.
def _g2_cofactor():
    t = -BLS_X + 1
    t2 = t * t - 2 * Q
    f2_sq, rem = divmod(4 * Q * Q - t2 * t2, 3)
    assert rem == 0
    import math

    f2 = math.isqrt(f2_sq)
    assert f2 * f2 == f2_sq
    for trace in ((3 * f2 + t2) // 2, (3 * f2 - t2) // 2,
                  (-3 * f2 + t2) // 2, (-3 * f2 - t2) // 2):
        order = Q * Q + 1 - trace
        if order % R == 0:
            return order // R
    raise AssertionError("no r-divisible sextic twist order")


_G2_COFACTOR = _g2_cofactor()


def _fq2_sqrt(a):
    """Square root in Fq2 (q^2 = 9 mod 16 branch handled via the generic
    Tonelli-style candidates)."""
    # candidate a^((q^2+7)/16) times one of the 8th roots of unity
    c = _fq2_pow(a, (Q * Q + 7) // 16)
    for mul in _SQRT_CANDS:
        cand = fq2_mul(c, mul)
        if fq2_mul(cand, cand) == a:
            return cand
    return None


def _fq2_pow(x, n):
    result = FQ2_ONE
    base = x
    while n:
        if n & 1:
            result = fq2_mul(result, base)
        base = fq2_mul(base, base)
        n >>= 1
    return result


# 8th roots of unity in Fq2 (candidates for sqrt adjustment)
_SQRT_CANDS = [
    (1, 0),
    _fq2_pow((1, 1), (Q * Q - 1) // 8) if Q else (1, 0),
]
_SQRT_CANDS.append(fq2_mul(_SQRT_CANDS[1], _SQRT_CANDS[1]))
_SQRT_CANDS.append(fq2_mul(_SQRT_CANDS[2], _SQRT_CANDS[1]))


def hash_to_g2(msg: bytes):
    """Deterministic map msg -> G2 subgroup point (try-and-increment +
    cofactor clearing)."""
    counter = 0
    while True:
        h = hashlib.sha512(b"BLS_H2G2" + counter.to_bytes(4, "big")
                           + msg).digest()
        x0 = int.from_bytes(h[:32], "big") % Q
        x1 = int.from_bytes(h[32:], "big") % Q
        x = (x0, x1)
        y2 = fq2_add(fq2_mul(fq2_mul(x, x), x), _fq2.b)
        y = _fq2_sqrt(y2)
        if y is not None:
            pt = _jac_mul((x, y), _G2_COFACTOR, _fq2)
            if pt is not None:
                return pt
        counter += 1


# ---------------------------------------------------------------------------
# BLS signatures (pk in G1, sig in G2 — the reference's production bench
# convention, off-chain-benchmarking/production/src/main.rs)
# ---------------------------------------------------------------------------

def key_gen(seed: bytes | None = None):
    if seed is None:
        sk = secrets.randbelow(R - 1) + 1
    else:
        sk = int.from_bytes(hashlib.sha512(seed).digest(), "big") % (R - 1) + 1
    return sk, g1_mul(g1_generator(), sk)


def sign(sk: int, msg: bytes):
    return g2_mul(hash_to_g2(msg), sk)


def verify(pk, msg: bytes, sig) -> bool:
    """e(g1, sig) == e(pk, H(m))  <=>  e(-g1, sig) * e(pk, H(m)) == 1."""
    if sig is None or not g2_on_curve(sig):
        return False
    f = multi_pairing([
        (g1_neg(g1_generator()), sig),
        (pk, hash_to_g2(msg)),
    ])
    return f == FQ12_ONE


def aggregate(sigs):
    agg = None
    for sig in sigs:
        agg = g2_add(agg, sig)
    return agg


def verify_aggregate(pks, msgs, agg_sig) -> bool:
    """Distinct messages: prod e(pk_i, H(m_i)) == e(g1, agg)."""
    if len(pks) != len(msgs):
        return False  # zip would silently verify a different statement
    if agg_sig is None or not g2_on_curve(agg_sig):
        return False
    pairs = [(g1_neg(g1_generator()), agg_sig)]
    pairs += [(pk, hash_to_g2(msg)) for pk, msg in zip(pks, msgs)]
    return multi_pairing(pairs) == FQ12_ONE


def verify_aggregate_common(pks, msg: bytes, agg_sig) -> bool:
    """Common message: aggregate the public keys first — 2 Miller loops
    regardless of signer count (the fast path the reference's bls branch
    uses for QC verification)."""
    if agg_sig is None or not g2_on_curve(agg_sig):
        return False
    apk = None
    for pk in pks:
        apk = g1_add(apk, pk)
    f = multi_pairing([
        (g1_neg(g1_generator()), agg_sig),
        (apk, hash_to_g2(msg)),
    ])
    return f == FQ12_ONE

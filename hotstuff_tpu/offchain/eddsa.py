"""Ed25519 scheme wrapper for the off-chain suite: host keygen/sign, and
three verify paths — host single (the reference's ed25519-dalek loop,
production/src/main.rs:19-64), TPU batch (this framework's device engine),
and host batch (sequential loop, the comparison baseline).
"""

from __future__ import annotations

from ..crypto import ref_ed25519 as _ref


def key_gen(seed: bytes):
    sk, pk = _ref.generate_keypair(seed)
    return sk, pk


def sign(sk: bytes, msg: bytes) -> bytes:
    return _ref.sign(sk, msg)


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Host single verify (OpenSSL-backed when available, else the pure
    reference implementation)."""
    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        try:
            Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False
    except ImportError:
        return _ref.verify(pk, msg, sig)


def verify_batch_host(msgs, pks, sigs):
    """Sequential host loop (what the reference's EdDSA bench measures)."""
    return [verify(pk, msg, sig) for msg, pk, sig in zip(msgs, pks, sigs)]


def verify_batch_tpu(msgs, pks, sigs):
    """Device batch verification (vmapped ladder; hotstuff_tpu/ops)."""
    from ..crypto import eddsa as device

    return list(device.verify_batch(msgs, pks, sigs))

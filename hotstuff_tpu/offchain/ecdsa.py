"""ECDSA over secp256k1 for the off-chain suite
(off-chain-benchmarking/ecdsa.py capability)."""

from __future__ import annotations

from . import secp256k1 as _c


def key_gen(seed: bytes | None = None):
    return _c.key_gen(seed)


def sign(sk: int, msg: bytes):
    return _c.ecdsa_sign(sk, msg)


def verify(pk, msg: bytes, sig) -> bool:
    return _c.ecdsa_verify(pk, msg, sig)


def verify_batch(msgs, pks, sigs):
    return [verify(pk, m, s) for m, pk, s in zip(msgs, pks, sigs)]

"""Off-chain digital-signature benchmarking suite: EdDSA (host + TPU
batch), ECDSA and Schnorr over secp256k1, and BLS12-381 with aggregation —
the capability of the reference's off-chain-benchmarking/ directory
(SURVEY.md §2.1) with pure-Python + JAX implementations."""

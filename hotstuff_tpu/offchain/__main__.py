"""CLI for the off-chain signature benchmarks (the reference's
off-chain-benchmarking/main.py entry point):

  python -m hotstuff_tpu.offchain single [--iters 100]
  python -m hotstuff_tpu.offchain batch [--max 300] [--step 20] [--no-tpu]
  python -m hotstuff_tpu.offchain msglen
"""

from __future__ import annotations

import argparse

from . import bench


def main(argv=None):
    ap = argparse.ArgumentParser(prog="hotstuff_tpu.offchain")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("single", help="single sign/verify latency")
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--schemes", nargs="*",
                   default=["eddsa", "ecdsa", "schnorr", "bls"])
    p.add_argument("--csv")

    p = sub.add_parser("batch", help="batch verify scaling sweep")
    p.add_argument("--min", type=int, default=20)
    p.add_argument("--max", type=int, default=300)
    p.add_argument("--step", type=int, default=20)
    p.add_argument("--no-tpu", action="store_true")
    p.add_argument("--csv")
    p.add_argument("--plot")

    p = sub.add_parser("msglen", help="verify cost vs message length")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--csv")

    args = ap.parse_args(argv)
    if args.command == "single":
        rows = bench.measure_single(iters=args.iters,
                                    schemes=tuple(args.schemes))
    elif args.command == "batch":
        sizes = tuple(range(args.min, args.max + 1, args.step))
        rows = bench.measure_batch(sizes=sizes, tpu=not args.no_tpu)
        if args.plot:
            bench.plot_batch(rows, args.plot)
    else:
        rows = bench.measure_message_length(iters=args.iters)
    if getattr(args, "csv", None):
        bench.to_csv(rows, args.csv)


if __name__ == "__main__":
    main()
